"""Continuous profiling plane (ISSUE 13): task-hop waterfalls, the
device-step/retrace profiler, and the HBM ledger.

* **Waterfall**: sampled tasks carry 7 phase stamps through spec + reply
  and the head folds reply_recv into per-phase histograms — ordering and
  monotonicity pinned across real task, actor, and nested hops; an
  UNSAMPLED context ships no stamps while its request id still reaches
  the head's task events (the zero-cost contract's forensic half).
* **Retrace detector**: a deliberately shape-varying jit call fires
  exactly once per NEW trace (``util.device_prof`` — RL014's runtime
  twin); a steady-state engine run fires zero; a storm trips the
  ``retrace-storm`` SLO rule through the live alerts engine.
* **HBM ledger**: the engine's byte gauges are conservation-checked
  against ``KVBlockPool.audit()`` block counts × block bytes.
"""

import json
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import metrics as um
from ray_tpu.util import tracing
from ray_tpu.util import waterfall as wfl
from ray_tpu.util.device_prof import JitProfiler


@pytest.fixture
def fresh_waterfall():
    wfl.clear()
    yield
    wfl.clear()


def _fold_total() -> int:
    return wfl.summary()["folded"]


# ---------------------------------------------------------------------------
# waterfall: stamping + folding across real hops
# ---------------------------------------------------------------------------


class TestWaterfall:
    def test_task_actor_nested_hops_fold_monotone(self, fresh_waterfall):
        # the per-leg histogram is process-lifetime (like every metric):
        # earlier tests in one pytest process may have folded sampled
        # tasks of their own, so every count assertion is a DELTA
        base = {
            name: wfl.summary()["legs"][name]["count"]
            for name, _i, _j in wfl.LEGS
        }
        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            from ray_tpu._private.runtime import get_ctx

            @ray_tpu.remote
            def leaf(x):
                return x + 1

            @ray_tpu.remote
            def parent(x):
                # nested hop: the worker's (sampled) context re-ships and
                # the nested spec earns its own stamp list
                return ray_tpu.get(leaf.remote(x)) + 10

            @ray_tpu.remote
            class Act:
                def m(self, x):
                    return x * 2

            with tracing.trace_context() as rid:
                for i in range(5):
                    assert ray_tpu.get(leaf.remote(i)) == i + 1
                assert ray_tpu.get(parent.remote(1)) == 12
                a = Act.remote()
                assert ray_tpu.get(a.m.remote(3)) == 6
            s = get_ctx().call("waterfall", recent=64)
            # 5 leaves + parent + nested leaf + actor method = 8 folds
            assert s["folded"] == 8
            assert s["incomplete"] == 0
            for name, _i, _j in wfl.LEGS:
                assert s["legs"][name]["count"] - base[name] == 8, name
            names = set()
            for rec in s["recent"]:
                stamps = rec["stamps"]
                assert len(stamps) == len(wfl.PHASES)
                assert stamps == sorted(stamps), (
                    f"non-monotone stamps for {rec.get('name')}: {stamps}"
                )
                assert rec["request_id"] == rid
                assert all(v >= 0 for v in rec["legs"].values())
                names.add(rec.get("name"))
            assert "Act.m" in names  # the actor hop folded
            assert any(n and "leaf" in n for n in names)
        finally:
            ray_tpu.shutdown()

    def test_unsampled_ships_no_stamps_but_keeps_ids(
        self, fresh_waterfall, monkeypatch
    ):
        monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "0")
        ray_tpu.init(num_cpus=1, num_tpus=0)
        try:
            from ray_tpu._private.runtime import get_ctx
            from ray_tpu.util import state as st

            @ray_tpu.remote
            def f(x):
                return x

            before = get_ctx().call("waterfall")["folded"]
            with tracing.trace_context() as rid:
                assert ray_tpu.get(f.remote(1)) == 1
            # rootless too: no context at all
            assert ray_tpu.get(f.remote(2)) == 2
            s = get_ctx().call("waterfall")
            assert s["folded"] == before  # nothing stamped, nothing folded
            assert s["incomplete"] == 0
            # the request id still reaches the head's task events (the
            # unsampled token rides the spec; forensics stay correlated)
            rids = {t.get("request_id") for t in st.get_task_events()}
            assert rid in rids
        finally:
            ray_tpu.shutdown()

    def test_error_and_retry_replies_count_incomplete(self, fresh_waterfall):
        """A task that raises never stamps exec_end: the head counts the
        partial list instead of folding a bogus record."""
        ray_tpu.init(num_cpus=1, num_tpus=0)
        try:
            from ray_tpu._private.runtime import get_ctx

            @ray_tpu.remote
            def boom():
                raise ValueError("x")

            with tracing.trace_context():
                with pytest.raises(ValueError):
                    ray_tpu.get(boom.remote())
            s = get_ctx().call("waterfall")
            assert s["folded"] == 0
            assert s["incomplete"] >= 1
        finally:
            ray_tpu.shutdown()

    def test_fold_unit_legs_and_clamp(self, fresh_waterfall):
        t0 = 1000.0
        stamps = [t0 + i * 0.001 for i in range(7)]
        assert wfl.fold(list(stamps), {"name": "t", "kind": "task"})
        s = wfl.summary(recent=1)
        rec = s["recent"][0]
        assert len(rec["stamps"]) == 8
        for name, i, j in wfl.LEGS:
            if name != "total" and j < 7:
                assert rec["legs"][name] == pytest.approx(0.001)
        # short/partial lists refuse to fold
        assert not wfl.fold(list(stamps[:5]))
        assert s["folded"] == 1
        # a wall-clock step backwards clamps to zero, never negative
        bad = [t0, t0 - 5.0] + [t0 + i for i in range(1, 6)]
        assert wfl.fold(bad)
        rec2 = wfl.summary(recent=1)["recent"][-1]
        assert rec2["legs"]["submit"] == 0.0

    def test_chrome_slices_nest_legs_inside_total(self, fresh_waterfall):
        stamps = [1000.0 + i * 0.01 for i in range(7)]
        wfl.fold(list(stamps), {
            "name": "noop", "kind": "task", "trace_ctx": {"request_id": "ab"},
        })
        slices = wfl.chrome_slices(wfl.summary(recent=4)["recent"])
        assert len(slices) == 1 + (len(wfl.LEGS) - 1)
        total = slices[0]
        assert total["pid"] == "waterfall" and total["tid"] == "req:ab"
        for leg in slices[1:]:
            assert leg["ts"] >= total["ts"]
            # 1µs slack: ts*1e6 sits near 1e15 where float ulp ≈ 0.25µs
            assert leg["ts"] + leg["dur"] <= total["ts"] + total["dur"] + 1.0


class TestWaterfallCLI:
    def test_obs_waterfall_probe_reports_8_phases(self, fresh_waterfall, capsys):
        from ray_tpu.obs import main as obs_main

        rc = obs_main(["waterfall", "--probe", "25", "--json"])
        out = capsys.readouterr().out
        assert rc == 0
        s = json.loads(out)
        assert s["folded"] >= 25
        assert len(s["phases"]) == 8
        legs = s["legs"]
        assert len(legs) == 8  # 7 hop legs + total
        for name, _i, _j in wfl.LEGS:
            assert legs[name]["count"] >= 25
            assert legs[name]["p50"] >= 0.0
            assert legs[name]["p99"] >= legs[name]["p50"] - 1e-9

    def test_top_row_dash_below_two_samples(self):
        from ray_tpu.obs import waterfall_top_row

        row = waterfall_top_row({"legs": {"submit": {"count": 1}}})
        # every leg below 2 samples renders the dash, never a number
        assert row.count("—") == len(wfl.LEGS)
        row2 = waterfall_top_row({
            "legs": {
                name: {"count": 5, "p50": 1e-4, "p99": 2e-3}
                for name, _i, _j in wfl.LEGS
            }
        })
        assert "—" not in row2
        assert "submit=100us/2.0ms" in row2

    def test_render_waterfall_table(self):
        from ray_tpu.obs import render_waterfall

        s = {
            "folded": 3, "incomplete": 1,
            "legs": {
                name: {"count": 3, "p50": 1e-4, "p95": 1e-3, "p99": 2e-3}
                for name, _i, _j in wfl.LEGS
            },
        }
        txt = render_waterfall(s)
        for name, _i, _j in wfl.LEGS:
            assert name in txt
        assert "3 folded" in txt


# ---------------------------------------------------------------------------
# device-step profiler: retrace goldens
# ---------------------------------------------------------------------------


class TestRetraceDetector:
    def test_shape_varying_jit_fires_once_per_new_trace(self, monkeypatch):
        import jax
        import jax.numpy as jnp

        from ray_tpu._private import events

        events.set_enabled(True)
        fn = jax.jit(lambda x: x * 2)
        prof = JitProfiler(event="llm.retrace")
        before = [
            e for e in events.snapshot() if e["type"] == "llm.retrace"
        ]

        def call(n):
            t0 = time.perf_counter()
            out = fn(jnp.ones(n))
            return prof.note("probe_site", fn, time.perf_counter() - t0)

        assert call(4) is False      # warmup: sets the baseline
        assert call(4) is False      # cached: no retrace
        assert call(8) is True       # NEW trace after warmup: fires
        assert call(8) is False      # that shape is warm now
        assert call(16) is True      # each new trace fires exactly once
        st = prof.stats()["probe_site"]
        assert st["retraces"] == 2
        assert st["calls"] == 5
        evs = [
            e for e in events.snapshot()
            if e["type"] == "llm.retrace" and e.get("site") == "probe_site"
        ]
        assert len(evs) - len([e for e in before if e.get("site") == "probe_site"]) == 2

    def test_plain_callable_never_fires(self):
        prof = JitProfiler()

        def plain():
            return None

        for _ in range(5):
            assert prof.note("plain", plain, 1e-4) is False
        assert prof.retraces == 0

    def test_engine_steady_state_zero_retraces(self):
        import jax

        from ray_tpu.llm.engine import EngineConfig, LLMEngine
        from ray_tpu.llm.scheduler import SamplingParams
        from ray_tpu.models.gpt import GPTConfig, gpt_init

        cfg = GPTConfig(vocab_size=64, seq_len=64, d_model=32, n_layers=2, n_heads=2)
        params = gpt_init(jax.random.PRNGKey(0), cfg)
        eng = LLMEngine(
            cfg, params,
            EngineConfig(max_slots=2, num_blocks=16, block_size=8,
                         max_blocks_per_seq=8, spec_k=2),
        )
        eng.warmup()
        for prompt in ([1, 2, 3], [4, 5, 6, 7], [1, 2, 3]):
            eng.generate(prompt, SamplingParams(max_tokens=6))
        assert eng.runner.prof.retraces == 0, eng.runner.prof.stats()
        assert eng.stats()["retraces"] == 0

    def test_profiled_train_step_counts_and_detects(self):
        import jax
        import optax

        from ray_tpu.parallel.mesh import MeshConfig, make_mesh
        from ray_tpu.parallel.train_step import (
            build_train_step,
            profile_step_fn,
        )

        mesh = make_mesh(MeshConfig(dp=-1, fsdp=1, tp=1))
        init_fn, raw_step = build_train_step(
            lambda p, b: ((p["w"] * b) ** 2).mean(), optax.sgd(0.1), mesh
        )
        step = profile_step_fn(raw_step)
        assert step.__wrapped__ is raw_step
        state = init_fn({"w": np.ones(8, np.float32)})
        batch = np.ones((8, 8), np.float32)
        for _ in range(3):
            state, _loss = step(state, batch)
        st = step.profiler.stats()["train_step"]
        assert st["calls"] == 3
        assert st["retraces"] == 0


class TestRetraceSLO:
    def test_rule_golden_fires_on_any_retrace_window(self):
        from ray_tpu.util import slo

        rule = next(
            r for r in slo.default_rules() if r.name == "retrace-storm"
        )
        assert rule.metric == "device_retraces"
        now = 1000.0
        merged = {
            "device_retraces": {
                "kind": "counter",
                "series": {
                    '{"site":"decode"}': [(now - 90, 0.0), (now - 30, 3.0)]
                },
            }
        }
        res = slo.evaluate_rule(rule, merged, now=now)
        assert res["breached"], res
        # zero retraces = no evidence, never a breach
        quiet = {
            "device_retraces": {
                "kind": "counter",
                "series": {'{"site":"decode"}': [(now - 90, 3.0), (now - 30, 3.0)]},
            }
        }
        assert not slo.evaluate_rule(rule, quiet, now=now)["breached"]
        assert not slo.evaluate_rule(rule, {}, now=now)["breached"]

    def test_retrace_trips_live_alerts_engine(self, monkeypatch):
        """The acceptance path: a site recompiling after warmup →
        device_retraces increments → series ship → the retrace-storm rule
        FIRES through the same alerts surface ``obs alerts --eval-once``
        reads."""
        import jax
        import jax.numpy as jnp

        monkeypatch.setenv("RAY_TPU_ALERTS_INTERVAL_S", "3600")  # manual ticks
        um._reset_series_for_tests()
        ray_tpu.init(num_cpus=1, num_tpus=0)
        try:
            from ray_tpu._private.runtime import get_ctx

            ctx = get_ctx()
            # baseline sample so the window has a point to diff against
            prof = JitProfiler(event="llm.retrace")
            fn = jax.jit(lambda x: x + 1)

            def call(n):
                t0 = time.perf_counter()
                fn(jnp.ones(n))
                prof.note("slo_probe", fn, time.perf_counter() - t0)

            call(2)  # warmup/baseline
            um.sample_series_now()
            um.flush()
            alerts = ctx.call("alerts", eval_now=True)
            by_rule = {a["rule"]: a for a in alerts}
            assert by_rule["retrace-storm"]["status"] != "FIRING"
            for n in (3, 4, 5):  # the storm
                call(n)
            assert prof.stats()["slo_probe"]["retraces"] == 3
            um.sample_series_now()
            um.flush()
            alerts = ctx.call("alerts", eval_now=True)
            by_rule = {a["rule"]: a for a in alerts}
            assert by_rule["retrace-storm"]["status"] == "FIRING", by_rule[
                "retrace-storm"
            ]
        finally:
            ray_tpu.shutdown()
            um._reset_series_for_tests()


# ---------------------------------------------------------------------------
# HBM ledger conservation
# ---------------------------------------------------------------------------


class TestHBMLedger:
    def _engine(self, **kw):
        import jax

        from ray_tpu.llm.engine import EngineConfig, LLMEngine
        from ray_tpu.models.gpt import GPTConfig, gpt_init

        cfg = GPTConfig(vocab_size=64, seq_len=128, d_model=32, n_layers=2, n_heads=2)
        params = gpt_init(jax.random.PRNGKey(0), cfg)
        eng_cfg = EngineConfig(
            max_slots=2, num_blocks=24, block_size=8, max_blocks_per_seq=16, **kw
        )
        return LLMEngine(cfg, params, eng_cfg), params

    def test_conservation_against_pool_audit(self):
        import jax

        from ray_tpu.llm.scheduler import SamplingParams

        eng, params = self._engine()
        eng.warmup()
        # long shared prompts so full prompt blocks become cache-resident
        base = list(range(1, 25))
        eng.generate(base + [30], SamplingParams(max_tokens=4))
        eng.generate(base + [31], SamplingParams(max_tokens=4))
        bb = eng.pool.block_bytes
        usable = eng.pool.cfg.num_blocks - 1

        def check(led, aud):
            # the ledger IS the audit's partition, in bytes
            assert led["seq_bytes"] == aud["owned"] * bb
            assert led["cache_bytes"] == aud["cached_only"] * bb
            assert led["free_bytes"] == aud["free"] * bb
            assert (
                led["seq_bytes"] + led["cache_bytes"] + led["free_bytes"]
                == usable * bb
            )

        led = eng.hbm_ledger()
        aud = eng.pool.audit()
        assert aud["ok"], aud
        check(led, aud)
        # both requests finished: their prompt blocks stay resident ONLY
        # for the prefix tree (the reclaimable tier the spill signal reads)
        assert led["cache_bytes"] > 0
        # one still-running request: it MATCHES the cached prefix, so the
        # shared blocks move from cache-only into seq-owned while the
        # partition stays exact
        req = eng.submit(base + [32], SamplingParams(max_tokens=64))
        for _ in range(8):
            eng.step()
        assert not req.finished
        led = eng.hbm_ledger()
        aud = eng.pool.audit()
        assert aud["ok"], aud
        check(led, aud)
        assert led["seq_bytes"] > 0
        # params accounting matches the real device arrays
        assert led["params_bytes"] == sum(
            int(leaf.nbytes) for leaf in jax.tree_util.tree_leaves(params)
        )
        assert led["pool_bytes"] == eng.pool.k.nbytes + eng.pool.v.nbytes
        assert led["pool_bytes"] == eng.pool.cfg.num_blocks * bb
        req.cancelled.set()
        while not req.finished:
            eng.step()

    def test_gauges_published_through_metrics(self):
        from ray_tpu.llm.scheduler import SamplingParams

        eng, _params = self._engine()
        eng.warmup()
        eng.generate([1, 2, 3], SamplingParams(max_tokens=2))
        led = eng.hbm_ledger()
        # local registry snapshot (no cluster needed): gauges are
        # last-write-wins, so the values are THIS engine's newest publish
        data = {
            m.name: m._snapshot()["data"]
            for m in um._registry
            if m.name.startswith("llm_hbm_")
        }
        for metric, key in (
            ("llm_hbm_params_bytes", "params_bytes"),
            ("llm_hbm_kv_pool_bytes", "pool_bytes"),
            ("llm_hbm_kv_seq_bytes", "seq_bytes"),
            ("llm_hbm_kv_cache_bytes", "cache_bytes"),
            ("llm_hbm_kv_free_bytes", "free_bytes"),
            ("llm_hbm_drafter_bytes", "drafter_bytes"),
        ):
            vals = list(data.get(metric, {}).values())
            assert vals, f"{metric} never published"
            assert vals[0] == led[key], (metric, vals, led)

    def test_drafter_bytes_counted_for_model_drafter(self):
        import jax

        from ray_tpu.llm.engine import EngineConfig, LLMEngine
        from ray_tpu.models.gpt import GPTConfig, gpt_init

        cfg = GPTConfig(vocab_size=64, seq_len=64, d_model=32, n_layers=2, n_heads=2)
        params = gpt_init(jax.random.PRNGKey(0), cfg)
        dcfg = GPTConfig(vocab_size=64, seq_len=32, d_model=16, n_layers=1, n_heads=2)
        dparams = gpt_init(jax.random.PRNGKey(1), dcfg)
        eng = LLMEngine(
            cfg, params,
            EngineConfig(max_slots=2, num_blocks=16, block_size=8,
                         max_blocks_per_seq=8, spec_k=2, spec_drafter="model"),
            draft_model_cfg=dcfg, draft_params=dparams,
        )
        expect = sum(
            int(leaf.nbytes) for leaf in jax.tree_util.tree_leaves(dparams)
        )
        assert eng.hbm_ledger()["drafter_bytes"] == expect
        # the n-gram drafter holds no device state
        eng2, _ = self._engine(spec_k=2)
        assert eng2.hbm_ledger()["drafter_bytes"] == 0


# ---------------------------------------------------------------------------
# registries: the profiling plane stays RL012-clean by construction
# ---------------------------------------------------------------------------


class TestRegistries:
    def test_grafana_profiling_row_tracks_registries(self):
        from ray_tpu.util import device_prof
        from ray_tpu.util.grafana import _profiling_panels

        exprs = " ".join(expr for _t, expr, _u, _d in _profiling_panels())
        for name in wfl.METRIC_NAMES[:1] + device_prof.METRIC_NAMES:
            assert name in exprs, f"profiling row lost {name}"
        for name in (
            "llm_hbm_params_bytes", "llm_hbm_kv_seq_bytes",
            "llm_hbm_kv_cache_bytes", "llm_hbm_kv_free_bytes",
            "llm_hbm_drafter_bytes", "llm_hbm_kv_pool_bytes",
        ):
            assert name in exprs, f"profiling row lost {name}"

    def test_metric_names_registered(self):
        from ray_tpu.llm import engine as eng_mod
        from ray_tpu.util import device_prof

        assert "core_task_phase_s" in wfl.METRIC_NAMES
        assert "device_retraces" in device_prof.METRIC_NAMES
        for n in (
            "llm_hbm_params_bytes", "llm_hbm_kv_pool_bytes",
            "llm_hbm_kv_seq_bytes", "llm_hbm_kv_cache_bytes",
            "llm_hbm_kv_free_bytes", "llm_hbm_drafter_bytes",
        ):
            assert n in eng_mod.METRIC_NAMES
