"""Peer-to-peer object plane: bytes move host-to-host through per-node data
servers; the head is directory only.

Reference: ``src/ray/object_manager/object_manager.h:117`` (node-to-node
chunked transfer), ``pull_manager.cc:48`` / ``push_manager.h:30``. The
"hosts" here are separate agent processes on loopback — same wire path as
real hosts. RAY_TPU_FORCE_DATA_PLANE=1 makes consumers skip the same-machine
shm shortcut so the test exercises the actual network path.
"""

import os
import tempfile
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import shm_store
from ray_tpu._private.config import resolve_authkey
from ray_tpu._private.head import Head
from ray_tpu._private.node_agent import NodeAgent


@pytest.fixture
def p2p_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_FORCE_DATA_PLANE", "1")
    authkey = resolve_authkey()
    session = tempfile.mkdtemp(prefix="ray_tpu_p2p_")
    head = Head(os.path.join(session, "head.sock"), authkey=authkey)
    head.start()
    host, port = head.listen_tcp("127.0.0.1", 0)
    head.add_node({"CPU": 0.0})
    addr = f"{host}:{port}"
    a = NodeAgent(addr, authkey, resources={"CPU": 2.0, "nodeA": 10.0}).start()
    b = NodeAgent(addr, authkey, resources={"CPU": 2.0, "nodeB": 10.0}).start()
    yield {"head": head, "a": a, "b": b, "address": addr}
    try:
        ray_tpu.shutdown()
    except Exception:
        pass
    a.shutdown()
    b.shutdown()
    head.shutdown()


SIZE = 8 * 1024 * 1024  # 8 MB payload -> multiple data-plane chunks at 8M? no: 1 chunk; still >> inline


def test_p2p_fetch_bypasses_head(p2p_cluster):
    ray_tpu.init(address=p2p_cluster["address"])
    head = p2p_cluster["head"]

    @ray_tpu.remote(resources={"nodeA": 1.0})
    def produce():
        return np.arange(SIZE // 8, dtype=np.int64)

    @ray_tpu.remote(resources={"nodeB": 1.0})
    def consume(arr):
        return int(arr[::4096].sum())

    ref = produce.remote()
    expect = int(np.arange(SIZE // 8, dtype=np.int64)[::4096].sum())
    assert ray_tpu.get(consume.remote(ref), timeout=60) == expect

    # the bytes moved A -> B directly: A's data server served them, and the
    # head shipped ZERO object bytes inline (directory role only)
    assert head.inline_bytes_served == 0
    assert p2p_cluster["a"].data_server.bytes_served >= SIZE


def test_result_bytes_live_on_producing_host(p2p_cluster):
    ray_tpu.init(address=p2p_cluster["address"])

    @ray_tpu.remote(resources={"nodeA": 1.0})
    def produce():
        return np.ones(SIZE // 8, dtype=np.int64)

    ref = produce.remote()
    # wait for completion via a driver get: the driver (same machine in this
    # test) still resolves through the locator; the locator must point at A
    out = ray_tpu.get(ref, timeout=60)
    assert out.shape == (SIZE // 8,)
    with p2p_cluster["head"].lock:
        ents = [
            e
            for e in p2p_cluster["head"].objects.values()
            if e.shm is not None and e.shm.node == p2p_cluster["a"].node_id_bin
        ]
    assert ents, "producer's result locator should carry the producing node"


def test_free_routes_to_owning_host(p2p_cluster):
    ray_tpu.init(address=p2p_cluster["address"])
    agent = p2p_cluster["a"]
    arena = shm_store.attach_arena(agent.arena_name)
    base = arena.n_objects

    @ray_tpu.remote(resources={"nodeA": 1.0})
    def produce():
        return np.zeros(SIZE // 8, dtype=np.int64)

    ref = produce.remote()
    ray_tpu.wait([ref], timeout=60)
    assert arena.n_objects == base + 1  # result landed in A's arena
    del ref
    import gc

    gc.collect()
    deadline = time.monotonic() + 20
    while arena.n_objects != base and time.monotonic() < deadline:
        time.sleep(0.1)
    assert arena.n_objects == base  # head routed the free to A's agent


def test_owner_node_death_recovers_via_lineage(p2p_cluster):
    ray_tpu.init(address=p2p_cluster["address"])
    head = p2p_cluster["head"]

    @ray_tpu.remote(resources={"nodeA": 1.0}, max_retries=2)
    def produce():
        return np.full(SIZE // 8, 7, dtype=np.int64)

    ref = produce.remote()
    ray_tpu.wait([ref], timeout=60)
    # A dies; its bytes are gone. The head must rebuild via lineage — but
    # the task is pinned to nodeA resources, so re-add capacity via B? No:
    # kill A's node, then the resubmitted task becomes infeasible until A's
    # agent re-registers. Use a second agent with nodeA resources instead.
    from ray_tpu._private.ids import NodeID
    from ray_tpu._private.node_agent import NodeAgent as NA

    a2 = NA(p2p_cluster["address"], resolve_authkey(), resources={"CPU": 2.0, "nodeA": 10.0}).start()
    try:
        head.remove_node(NodeID(p2p_cluster["a"].node_id_bin))
        out = ray_tpu.get(ref, timeout=60)
        assert (out[::4096] == 7).all()
    finally:
        a2.shutdown()
