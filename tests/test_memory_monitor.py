"""Memory monitor + OOM killing (reference: ``src/ray/common/
memory_monitor.h:52`` + ``worker_killing_policy_retriable_fifo.h:31``)."""

import time

import pytest

import ray_tpu
from ray_tpu._private.runtime import get_ctx
from ray_tpu.exceptions import OutOfMemoryError


@pytest.fixture
def oom_cluster():
    ray_tpu.init(
        num_cpus=2,
        _system_config={
            "memory_monitor_refresh_ms": 50,
            "memory_usage_threshold": 0.9,
        },
    )
    yield get_ctx().head
    ray_tpu.shutdown()


def test_oom_kill_retries_then_fails(oom_cluster):
    head = oom_cluster

    @ray_tpu.remote(max_retries=1)
    def hog():
        time.sleep(30)
        return "finished"

    fut = hog.remote()
    time.sleep(0.5)  # let it start
    head._memory_sampler = lambda: 0.99  # inject pressure
    try:
        with pytest.raises(OutOfMemoryError):
            ray_tpu.get(fut, timeout=60)
    finally:
        head._memory_sampler = None
    events = [e for e in head.rpc_task_events() if e["state"] == "OOM_KILLED"]
    assert len(events) >= 2  # first run + its retry both OOM-killed


def test_no_kill_below_threshold(oom_cluster):
    head = oom_cluster
    head._memory_sampler = lambda: 0.5

    @ray_tpu.remote
    def quick():
        time.sleep(0.3)
        return 7

    try:
        assert ray_tpu.get(quick.remote(), timeout=60) == 7
    finally:
        head._memory_sampler = None
    assert not [e for e in head.rpc_task_events() if e["state"] == "OOM_KILLED"]


def test_memory_usage_fraction_reads_proc(oom_cluster):
    frac = oom_cluster.memory_usage_fraction()
    assert 0.0 <= frac <= 1.0
