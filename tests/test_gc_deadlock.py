"""Regression tests for GC-reentrancy deadlocks.

Round-3 postmortem: a worker-IO thread held the head lock through
``rpc_create_actor -> ... -> _start_actor_on -> Thread.start()``; the child
thread's bootstrap hit a GC tick that ran ``ObjectRef.__del__`` ->
``free_ref_async`` -> a SYNCHRONOUS ``head.remove_ref`` -> blocked on the held
head lock, while the parent sat in ``Thread.start()`` waiting for the child.
The fix (a) routes every ``__del__``-reachable runtime touch through a
reentrant ``SimpleQueue`` drained off-thread (reference: the reference never
blocks in a destructor — decrements post to the io_context,
``src/ray/core_worker/reference_count.h:61``), and (b) moves worker spawning
to a dispatcher thread so ``Thread.start()`` never runs under the head lock.
"""

import gc
import threading
import time

import ray_tpu
from ray_tpu._private import runtime


def test_del_never_blocks_on_head_lock(ray_start_regular):
    """Deterministic replay of the round-3 wedge: drop an owned ObjectRef in
    a side thread WHILE this thread holds the head lock. Pre-fix, the side
    thread blocked in remove_ref forever; post-fix, __del__ only enqueues."""
    ctx = runtime.get_ctx()
    box = [ray_tpu.put(b"y" * 32)]
    oid = box[0].binary()
    done = threading.Event()

    def drop():
        box.pop()  # last handle -> __del__ fires here
        gc.collect()
        done.set()

    with ctx.head.lock:
        t = threading.Thread(target=drop, daemon=True)
        t.start()
        assert done.wait(timeout=10), (
            "ObjectRef.__del__ blocked while the head lock was held "
            "(GC-reentrancy deadlock regression)"
        )
    # the drain thread now performs the real decrement -> eviction
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with ctx.head.lock:
            ent = ctx.head.objects.get(oid)
            if ent is None or ent.refcount <= 0:
                return
        time.sleep(0.05)
    raise AssertionError("queued free was never drained (refcount still held)")


def test_actor_spawn_under_gc_storm(ray_start_regular):
    """Allocation storm with owned refs dying inside reference cycles while
    actors spawn: GC ticks land in arbitrary threads (including worker-spawn
    bootstraps). Pre-fix this wedged GC-timing-dependently; the whole flow
    must complete within the deadline."""

    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    stop = threading.Event()

    def storm():
        while not stop.is_set():
            refs = [ray_tpu.put(b"x" * 64) for _ in range(32)]
            cyc = []
            for r in refs:
                d = {"ref": r}
                d["self"] = d  # cycle -> only the collector frees it
                cyc.append(d)
            del refs, cyc
            gc.collect()

    old = gc.get_threshold()
    gc.set_threshold(5, 2, 2)  # GC on nearly every allocation, every thread
    storm_t = threading.Thread(target=storm, daemon=True)
    storm_t.start()
    try:
        ok = []

        def spawn_and_call():
            actors = [A.remote() for _ in range(8)]
            assert ray_tpu.get([a.ping.remote() for a in actors]) == [1] * 8
            for a in actors:
                ray_tpu.kill(a)
            ok.append(True)

        w = threading.Thread(target=spawn_and_call, daemon=True)
        w.start()
        w.join(timeout=180)
        assert ok, "actor spawn wedged under GC storm (__del__ deadlock?)"
    finally:
        stop.set()
        gc.set_threshold(*old)
        storm_t.join(timeout=10)
