"""Durable head: full-table snapshot + reattach after a head crash.

Reference: GCS failover — every table persisted and reloaded
(``src/ray/gcs/gcs_server/gcs_table_storage.cc``, ``gcs_init_data.cc``),
raylets re-registering within the reconnect window
(``ray_config_def.h:56-60``). Here: the snapshot carries KV/functions,
detached actors, placement groups, and the durable slice of the object
directory; node agents reattach under their ORIGINAL node id and a
detached actor's surviving worker reconnects and rebinds with its state
intact."""

import os
import tempfile
import time

import numpy as np

import ray_tpu
from ray_tpu._private.config import GLOBAL_CONFIG, resolve_authkey
from ray_tpu._private.head import Head
from ray_tpu._private.node_agent import NodeAgent
from ray_tpu._private.runtime import ObjectRef, get_ctx


def _crash(head):
    """Simulate a head PROCESS crash: listeners and loops die; nothing is
    cleaned up — no worker kills, no arena unlink, no agent goodbyes."""
    from ray_tpu._private.head import _close_listener
    from ray_tpu._private.node_agent import shutdown_conn

    head._shutdown = True
    for listener in (head._listener, head._tcp_listener):
        _close_listener(listener)
    if head.data_server is not None:
        head.data_server.shutdown()
    # shutdown_conn (not close): a thread blocked in recv pins the socket,
    # so a bare close never sends FIN and peers would never notice
    for conn in list(head._io_conns):
        shutdown_conn(conn)
    with head.lock:
        for n in head.nodes.values():
            if n.agent is not None:
                shutdown_conn(n.agent.conn)


def test_head_restart_restores_cluster(tmp_path, monkeypatch):
    snap = str(tmp_path / "gcs.snap")
    monkeypatch.setattr(GLOBAL_CONFIG, "gcs_snapshot_path", snap)
    monkeypatch.setattr(GLOBAL_CONFIG, "head_reconnect_grace_s", 25.0)
    authkey = resolve_authkey()
    session = tempfile.mkdtemp(prefix="rtp_durable_")

    head_a = Head(os.path.join(session, "a.sock"), authkey=authkey)
    head_a.start()
    host, port = head_a.listen_tcp("127.0.0.1", 0)
    head_a.add_node({"CPU": 0.0})
    addr = f"{host}:{port}"
    agent = NodeAgent(addr, authkey, resources={"CPU": 2.0}).start()
    agent_node = agent.node_id_bin

    ray_tpu.init(address=addr)

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    # num_cpus=1 pins the actor to the agent node (the head node has CPU 0):
    # its worker is agent-spawned, talks TCP, and survives the head crash
    c = Counter.options(name="ctr", lifetime="detached", num_cpus=1).remote()
    assert ray_tpu.get(c.inc.remote(), timeout=60) == 1
    assert ray_tpu.get(c.inc.remote(), timeout=60) == 2

    get_ctx().call("kv_put", key="durable-k", value=b"durable-v")
    pg_id = head_a.create_pg([{"CPU": 1.0}], "PACK", name="pg1")

    # an object spilled to disk must survive the crash (bytes on disk)
    src = np.arange(100_000, dtype=np.int64)
    ref = ray_tpu.put(src)
    oid = ref.binary()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with head_a.lock:
            ent = head_a.objects.get(oid)
            if ent is not None and ent.ready:
                break
        time.sleep(0.05)
    with head_a.lock:
        head_a._spill_one(oid, head_a.objects[oid])
        assert head_a.objects[oid].spill_path is not None

    head_a._snapshot()
    assert os.path.exists(snap)

    ray_tpu.shutdown()
    _crash(head_a)

    # restart on the SAME port, fresh process state + snapshot
    head_b = Head(os.path.join(session, "b.sock"), authkey=authkey)
    head_b.start()
    head_b.listen_tcp("127.0.0.1", port)
    head_b.add_node({"CPU": 0.0})

    # tables restored
    with head_b.lock:
        assert head_b.kv.get("durable-k") == b"durable-v"
        assert pg_id in head_b.placement_groups
        assert "default:ctr" in head_b.named_actors  # keys are "namespace:name"
        assert oid in head_b.objects

    ray_tpu.init(address=addr)

    # the agent reattaches under its ORIGINAL node id within the grace
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with head_b.lock:
            n = head_b.nodes.get(agent_node)
            if n is not None and n.alive and n.agent is not None:
                break
        time.sleep(0.2)
    with head_b.lock:
        assert head_b.nodes.get(agent_node) is not None and head_b.nodes[agent_node].alive

    # the detached actor's surviving worker rebinds: state is PRESERVED
    c2 = ray_tpu.get_actor("ctr")
    assert ray_tpu.get(c2.inc.remote(), timeout=60) == 3

    # the spilled object restores transparently
    out = ray_tpu.get(ObjectRef(oid), timeout=60)
    assert (out[::9999] == src[::9999]).all()

    # the placement group re-places on the reattached agent's capacity
    assert head_b.pg_ready_wait(pg_id, timeout=30)

    ray_tpu.shutdown()
    agent.shutdown()
    head_b.shutdown()
