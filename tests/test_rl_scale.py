"""RL at scale (VERDICT r2 #9): Atari-style pixel learning through the
frame-connector pipeline, and multi-learner data-parallel LearnerGroups.

ALE is not installable in this image, so the Atari-class workload is
CatchPixelEnv — raw 84x84x3 RGB frames through the same
grayscale→resize→scale→frame-stack pipeline an ALE Pong setup uses
(reference: rllib/tuned_examples/impala pong family + the Atari wrapper
stack), with a CNN-encoder ActorCriticModule. The learning test is marked
slow.  Reference for the learner group: rllib/core/learner/learner_group.py:71
(N DDP learners; grads averaged, weights in lockstep)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl.connectors import (
    ConnectorPipeline,
    FrameStack,
    GrayscaleObservations,
    ResizeObservations,
    ScaleObservations,
)


def _frame_pipeline():
    return ConnectorPipeline(
        [
            GrayscaleObservations(),
            ResizeObservations(21, 21),
            ScaleObservations(),
            FrameStack(2),
        ]
    )


class TestFrameConnectors:
    def test_grayscale(self):
        rgb = np.zeros((2, 4, 4, 3), np.uint8)
        rgb[0, ..., 0] = 255  # pure red
        out = GrayscaleObservations()(rgb)
        assert out.shape == (2, 4, 4)
        assert abs(out[0, 0, 0] - 255 * 0.299) < 1e-3
        assert out[1].max() == 0

    def test_resize_nearest(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 4, 4)
        out = ResizeObservations(2, 2)(x)
        assert out.shape == (1, 2, 2)
        assert out[0, 0, 0] == x[0, 0, 0]

    def test_scale(self):
        assert ScaleObservations()(np.array([[255]], np.uint8))[0, 0] == pytest.approx(1.0)

    def test_frame_stack_and_episode_reset(self):
        fs = FrameStack(3)
        f = lambda v: np.full((2, 2, 2), v, np.float32)  # noqa: E731
        s1 = fs(f(1.0))
        assert s1.shape == (2, 2, 2, 3)
        assert (s1 == 1.0).all()  # first frame replicated
        s2 = fs(f(2.0))
        assert list(s2[0, 0, 0]) == [1.0, 1.0, 2.0]
        # env 0 ends an episode; its NEXT frame starts a fresh stack
        fs.observe_dones(np.array([True, False]))
        s3 = fs(f(3.0))
        assert list(s3[0, 0, 0]) == [3.0, 3.0, 3.0]
        assert list(s3[1, 0, 0]) == [1.0, 2.0, 3.0]

    def test_peek_gives_true_next_stack(self):
        fs = FrameStack(2)
        fs(np.full((1, 2, 2), 1.0, np.float32))
        nxt = fs.peek(np.full((1, 2, 2), 5.0, np.float32))
        assert list(nxt[0, 0, 0]) == [1.0, 5.0]  # slid, not replicated
        s = fs(np.full((1, 2, 2), 2.0, np.float32))  # state was untouched
        assert list(s[0, 0, 0]) == [1.0, 2.0]

    def test_pipeline_shapes_end_to_end(self):
        pipe = _frame_pipeline()
        frames = np.random.randint(0, 255, (4, 84, 84, 3), np.uint8)
        out = pipe(frames)
        assert out.shape == (4, 21, 21, 2)
        assert out.dtype == np.float32
        assert 0.0 <= out.min() and out.max() <= 1.0


def test_cnn_module_on_pixels():
    import jax

    from ray_tpu.rl.rl_module import ActorCriticModule, RLModuleSpec
    from ray_tpu.rl.spaces import Box, Discrete

    spec = RLModuleSpec(Box(0, 1, shape=(21, 21, 2)), Discrete(3))
    mod = ActorCriticModule(spec)
    params = mod.init(jax.random.PRNGKey(0))
    assert "enc" in params
    obs = np.random.rand(5, 21, 21, 2).astype(np.float32)
    out = mod.apply(params, obs)
    assert out["logits"].shape == (5, 3)
    assert out["value"].shape == (5,)


@pytest.mark.slow
def test_pixel_catch_learns_with_frame_pipeline(ray_start_regular):
    """The Atari-class bar scaled to CI: IMPALA-family learning on raw
    pixels through the full frame pipeline, to a reward threshold within a
    bounded budget. Random play averages ~-1.8 on 3-ball Catch; solved is
    +3; the bar of >= +1.0 demonstrates genuine pixel learning."""
    from ray_tpu.rl.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment("CatchPixel-v0")
        .env_runners(
            num_env_runners=0,
            num_envs_per_env_runner=16,
            rollout_fragment_length=64,
            env_to_module_connector=_frame_pipeline,
        )
        .training(train_batch_size=1024, lr=1e-3, gamma=0.97)
        .build()
    )
    try:
        best = -3.0
        for it in range(40):
            result = algo.train()
            mean = result.get("episode_reward_mean")
            if mean is not None:
                best = max(best, mean)
            if best >= 1.0:
                break
        assert best >= 1.0, f"best episode_reward_mean {best}"
    finally:
        algo.stop()


# tier1-durations: ~25s on the CI box — the full suite overruns the
# 870s tier-1 budget (truncation, not failures; ROADMAP), so the heaviest
# non-LLM learning/scale tests run as @slow instead of being cut at random
@pytest.mark.slow
def test_learner_group_two_learners_match_single(ray_start_regular):
    """2 data-parallel learners must evolve weights IDENTICALLY to one
    learner on the full batch (grads averaged sample-weighted; every
    learner applies the same update — the DDP invariant)."""
    import jax

    from ray_tpu.rl.learner import Learner, LearnerGroup
    from ray_tpu.rl.rl_module import ActorCriticModule, RLModuleSpec
    from ray_tpu.rl.sample_batch import SampleBatch
    from ray_tpu.rl.spaces import Box, Discrete

    def module_factory():
        return ActorCriticModule(RLModuleSpec(Box(-1, 1, shape=(4,)), Discrete(2)))

    def loss_fn(module, params, batch):
        logp, entropy, value = module.logp_entropy_value(
            params, batch["obs"], batch["act"]
        )
        loss = -(logp * batch["adv"]).mean() + ((value - batch["ret"]) ** 2).mean()
        return loss, {"policy_loss": loss}

    kwargs = dict(module_factory=module_factory, loss_fn=loss_fn, lr=1e-2, seed=7)
    rng = np.random.default_rng(0)

    def make_batch(n=64):
        return SampleBatch(
            {
                "obs": rng.standard_normal((n, 4)).astype(np.float32),
                "act": rng.integers(0, 2, n),
                "adv": rng.standard_normal(n).astype(np.float32),
                "ret": rng.standard_normal(n).astype(np.float32),
            }
        )

    batches = [make_batch() for _ in range(4)]

    single = Learner(**kwargs)
    for b in batches:
        single.update(b)

    group = LearnerGroup(dict(kwargs), num_learners=2)
    try:
        for b in batches:
            metrics = group.update(b)
            assert "policy_loss" in metrics
        w_group = group.get_weights()
        w_single = single.get_weights()
        for leaf_g, leaf_s in zip(
            jax.tree_util.tree_leaves(w_group), jax.tree_util.tree_leaves(w_single)
        ):
            np.testing.assert_allclose(
                np.asarray(leaf_g), np.asarray(leaf_s), rtol=1e-4, atol=1e-5
            )
        # and BOTH learners hold identical weights (lockstep invariant)
        w0 = ray_tpu.get(group._actors[0].get_weights.remote())
        w1 = ray_tpu.get(group._actors[1].get_weights.remote())
        for a, b in zip(jax.tree_util.tree_leaves(w0), jax.tree_util.tree_leaves(w1)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        group.shutdown()
