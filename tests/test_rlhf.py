"""ray_tpu.rlhf: disaggregated async RL-on-LLM.

Unit pins (no cluster): staleness gate golden ratios + version-K drop
behavior, importance-ratio goldens, GRPO advantages, staging buffer.
Integration (cluster fixtures): chunked weight publication roundtrip,
engine hot-swap without draining, version stamping, the rollout
trajectory contract, the serve-hosted push path sharing the sync code
path, and (slow) the end-to-end async loop: reward improves while
rollout and learner provably overlap.
"""

import math
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import ray_tpu  # noqa: E402
from ray_tpu.llm.engine import EngineConfig, LLMEngine  # noqa: E402
from ray_tpu.llm.scheduler import SamplingParams  # noqa: E402
from ray_tpu.models.gpt import GPTConfig, gpt_init  # noqa: E402
from ray_tpu.rlhf import (  # noqa: E402
    Algorithm,
    RLHFConfig,
    RolloutWorker,
    TrajectoryBuffer,
    apply_weight_update,
    fetch_params,
    group_advantages,
    importance_ratios,
    publish_weights,
    staleness_weights,
)

TINY = GPTConfig(
    vocab_size=32, seq_len=64, d_model=32, n_layers=1, n_heads=2,
    remat=False, fused_loss=False, dtype="float32",
)
ENG = EngineConfig(
    max_slots=4, num_blocks=64, block_size=4, max_blocks_per_seq=8,
    prefill_chunk=8,
)


@pytest.fixture(scope="module")
def tiny_params():
    return gpt_init(jax.random.PRNGKey(0), TINY)


# ---------------------------------------------------------------------------
# unit: staleness gate + importance correction (golden-pinned)
# ---------------------------------------------------------------------------


class TestStalenessGate:
    def test_drop_mode_version_k_boundary(self):
        """The gate's contract: age <= K admits at full weight, age K+1
        drops — pinned exactly at the boundary."""
        w = staleness_weights([0, 1, 3, 4, 5, 9], max_staleness=4, mode="drop")
        np.testing.assert_array_equal(w, [1.0, 1.0, 1.0, 1.0, 0.0, 0.0])

    def test_downweight_mode_goldens(self):
        """Past the gate every halflife of extra age halves the weight:
        age K -> 1, K+1 -> 0.5, K+2 -> 0.25 (halflife=1)."""
        w = staleness_weights([0, 2, 3, 4, 6], max_staleness=2,
                              mode="downweight", halflife=1.0)
        np.testing.assert_allclose(w, [1.0, 1.0, 0.5, 0.25, 0.0625], atol=1e-7)

    def test_downweight_halflife_scales(self):
        w = staleness_weights([4], max_staleness=0, mode="downweight",
                              halflife=2.0)
        np.testing.assert_allclose(w, [0.25], atol=1e-7)

    def test_negative_age_counts_as_fresh(self):
        # an engine that applied a push before the learner's bookkeeping
        # stamps a FUTURE version; that is freshness, not staleness
        assert staleness_weights([-3], 0, "drop")[0] == 1.0

    def test_unknown_mode_raises(self):
        with pytest.raises(ValueError):
            staleness_weights([1], 1, mode="decay")


class TestImportanceCorrection:
    def test_ratio_goldens(self):
        """ratio = exp(cur - behavior), hand-computed."""
        behavior = np.log([0.5, 0.25, 0.1])
        current = np.log([0.25, 0.25, 0.2])
        r = importance_ratios(behavior, current)
        np.testing.assert_allclose(r, [0.5, 1.0, 2.0], atol=1e-6)

    def test_clip_golden(self):
        r = importance_ratios(
            np.log([0.5, 0.1, 0.4]), np.log([0.25, 0.9, 0.4]), clip=0.2
        )
        np.testing.assert_allclose(r, [0.8, 1.2, 1.0], atol=1e-6)

    def test_group_advantages_standardize(self):
        adv = group_advantages([1.0, 2.0, 3.0])
        np.testing.assert_allclose(adv.mean(), 0.0, atol=1e-6)
        np.testing.assert_allclose(adv.std(), 1.0, atol=1e-5)

    def test_group_advantages_zero_variance_is_zero(self):
        # no contrast, no gradient: a constant-reward batch must not
        # produce NaNs or a fake learning signal
        np.testing.assert_array_equal(group_advantages([0.3, 0.3, 0.3]),
                                      [0.0, 0.0, 0.0])


class TestTrajectoryBuffer:
    def test_fifo_and_overflow_drops_oldest(self):
        buf = TrajectoryBuffer(capacity=3)
        buf.add([{"i": k} for k in range(5)])
        assert [t["i"] for t in buf.take(3, timeout=1)] == [2, 3, 4]
        assert buf.stats()["dropped_overflow"] == 2

    def test_take_blocks_until_staged(self):
        buf = TrajectoryBuffer(capacity=8)
        got = []

        def consumer():
            got.extend(buf.take(2, timeout=5))

        th = threading.Thread(target=consumer)
        th.start()
        time.sleep(0.05)
        buf.add([{"i": 1}, {"i": 2}])
        th.join(timeout=5)
        assert len(got) == 2

    def test_take_timeout_returns_partial(self):
        buf = TrajectoryBuffer(capacity=8)
        buf.add([{"i": 1}])
        assert len(buf.take(4, timeout=0.05)) == 1


class TestLoss:
    def _batch(self, **over):
        B, T, O = 2, 8, 4
        base = dict(
            tokens=np.tile(np.arange(T, dtype=np.int32), (B, 1)),
            prompt_len=np.full(B, 3, np.int32),
            out_tokens=np.tile(np.arange(3, 3 + O, dtype=np.int32), (B, 1)),
            out_len=np.full(B, O, np.int32),
            behavior_logp=np.full((B, O), -2.0, np.float32),
            token_mask=np.ones((B, O), np.float32),
            advantage=np.asarray([1.0, -1.0], np.float32),
            weight=np.ones(B, np.float32),
            temperature=np.ones(B, np.float32),
            top_k=np.zeros(B, np.int32),
            top_p=np.ones(B, np.float32),
        )
        base.update(over)
        return {k: jnp.asarray(v) for k, v in base.items()}

    def test_token_mask_excludes_unknown_behavior(self, tiny_params):
        """A masked token must contribute NOTHING: garbage behavior_logp
        under mask 0 leaves the loss bit-identical (the failover-resume
        NaN contract)."""
        from ray_tpu.rlhf.learner import GPTPolicyModule, rlhf_loss

        module = GPTPolicyModule(TINY)
        loss_fn = rlhf_loss(clip_param=0.2)
        mask = np.ones((2, 4), np.float32)
        mask[0, 1] = 0.0
        blp = np.full((2, 4), -2.0, np.float32)
        l1, m1 = loss_fn(module, tiny_params,
                         self._batch(token_mask=mask, behavior_logp=blp))
        blp2 = blp.copy()
        blp2[0, 1] = 123.0  # garbage where masked
        l2, m2 = loss_fn(module, tiny_params,
                         self._batch(token_mask=mask, behavior_logp=blp2))
        assert float(l1) == float(l2)
        assert float(m1["kl"]) == float(m2["kl"])

    def test_kl_finite_when_current_filter_masks_behavior_token(
        self, tiny_params
    ):
        """top_k=1 under the CURRENT policy masks most behavior tokens
        (~-1e30 scores): ratio goes to 0 (clipped, fine) and the KL term
        must stay clamped-finite instead of exploding to ~1e30."""
        from ray_tpu.rlhf.learner import GPTPolicyModule, rlhf_loss

        module = GPTPolicyModule(TINY)
        loss_fn = rlhf_loss(clip_param=0.2, kl_coeff=0.01)
        loss, metrics = loss_fn(
            module, tiny_params, self._batch(top_k=np.ones(2, np.int32))
        )
        assert np.isfinite(float(loss))
        assert abs(float(metrics["kl"])) <= 20.0 + 1e-6


# ---------------------------------------------------------------------------
# engine hot-swap (no cluster)
# ---------------------------------------------------------------------------


class TestEngineHotSwap:
    def test_swap_without_draining_in_flight(self, tiny_params):
        """A weight push lands mid-generation: the in-flight request
        keeps its slot, finishes under the new weights, and keeps its
        submit-time version stamp; later submits stamp the new version."""
        eng = LLMEngine(TINY, tiny_params, ENG)
        req = eng.submit([1, 2, 3], SamplingParams(max_tokens=12,
                                                   temperature=1.0, seed=1))
        for _ in range(4):
            eng.step()
        mid = len(req.out)
        assert 0 < mid < 12 and req.weights_version == 0
        v = eng.update_weights(gpt_init(jax.random.PRNGKey(9), TINY), 1)
        assert v == 1
        while not req.finished:
            eng.step()
        assert len(req.out) == 12 and req.finish_reason == "length"
        assert req.weights_version == 0  # stamped at submit
        # every token has a captured behavior logprob across the swap
        assert not any(math.isnan(x) for x in req.out_logprobs)
        req2 = eng.submit([1], SamplingParams(max_tokens=2))
        assert req2.weights_version == 1

    def test_swap_changes_future_tokens_deterministically(self, tiny_params):
        """Same request params under v0 and under pushed v1 weights give
        different outputs, and v1 output equals a fresh v1 engine's (the
        swap installs exactly the pushed params)."""
        other = gpt_init(jax.random.PRNGKey(9), TINY)
        sp = SamplingParams(max_tokens=8, temperature=1.0, seed=4)

        def gen(engine):
            r = engine.submit([2, 3, 4], sp)
            while not r.finished:
                engine.step()
            return r.out

        e0 = LLMEngine(TINY, tiny_params, ENG)
        base = gen(e0)
        e0.update_weights(other, 1)
        swapped = gen(e0)
        fresh = gen(LLMEngine(TINY, other, ENG))
        assert swapped == fresh
        assert swapped != base  # different weights actually took effect

    def test_structure_and_shape_mismatch_rejected(self, tiny_params):
        eng = LLMEngine(TINY, tiny_params, ENG)
        with pytest.raises(ValueError, match="structure"):
            eng.update_weights({"not": np.zeros(2)}, 1)
        bigger = gpt_init(
            jax.random.PRNGKey(1),
            GPTConfig(vocab_size=32, seq_len=64, d_model=64, n_layers=1,
                      n_heads=2, remat=False, fused_loss=False,
                      dtype="float32"),
        )
        with pytest.raises(ValueError, match="leaf mismatch"):
            eng.update_weights(bigger, 1)

    def test_version_never_goes_backwards(self, tiny_params):
        eng = LLMEngine(TINY, tiny_params, ENG)
        eng.update_weights(tiny_params, 3)
        with pytest.raises(ValueError, match="backwards"):
            eng.update_weights(tiny_params, 2)
        # idempotent re-delivery of the same version is fine
        assert eng.update_weights(tiny_params, 3) == 3
        # default bumps
        assert eng.update_weights(tiny_params) == 4


# ---------------------------------------------------------------------------
# object-plane sync + rollout worker (cluster)
# ---------------------------------------------------------------------------


class TestWeightSync:
    def test_publish_fetch_roundtrip_chunked(self, ray_start_regular, tiny_params):
        """Tiny chunk_bytes forces many chunks; the reassembled pytree is
        bit-identical and structure-identical."""
        update = publish_weights(tiny_params, 7, chunk_bytes=16 << 10)
        assert update.version == 7
        assert len(update.chunk_refs) > 1  # actually chunked
        assert update.num_leaves == len(jax.tree_util.tree_leaves(tiny_params))
        got = fetch_params(update)
        leaves_a = jax.tree_util.tree_leaves(tiny_params)
        leaves_b = jax.tree_util.tree_leaves(got)
        assert len(leaves_a) == len(leaves_b)
        for a, b in zip(leaves_a, leaves_b):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_apply_weight_update_engine_path(self, ray_start_regular, tiny_params):
        eng = LLMEngine(TINY, tiny_params, ENG)
        other = gpt_init(jax.random.PRNGKey(9), TINY)
        update = publish_weights(other, 2)
        assert apply_weight_update(eng, update) == 2
        assert eng.weights_version == 2

    def test_rollout_worker_trajectory_contract(self, ray_start_regular):
        """Local-mode worker: trajectories carry tokens, finite behavior
        logprobs, the submit-time version stamp, and a finish reason."""
        w = RolloutWorker(model="gpt", model_cfg=TINY, engine_config=ENG,
                          seed=0, warmup=False)
        try:
            pending = w.submit([[1, 2, 3], [3, 2, 1]], max_tokens=5,
                               temperature=1.0)
            assert pending == 2
            deadline = time.time() + 30
            trajs = []
            while len(trajs) < 2 and time.time() < deadline:
                trajs += w.poll()["trajs"]
                time.sleep(0.01)
            assert len(trajs) == 2
            for t in trajs:
                assert len(t["tokens"]) == 5
                assert len(t["logprobs"]) == 5
                assert all(np.isfinite(t["logprobs"]))
                assert t["weights_version"] == 0
                assert t["finish_reason"] == "length"
            # push through the SAME path the group uses; next submits stamp v1
            other = gpt_init(jax.random.PRNGKey(9), TINY)
            assert w.update_weights(publish_weights(other, 1)) == 1
            w.submit([[1, 2]], max_tokens=2)
            deadline = time.time() + 30
            out = []
            while not out and time.time() < deadline:
                out = w.poll()["trajs"]
                time.sleep(0.01)
            assert out and out[0]["weights_version"] == 1
        finally:
            w.stop()

    def test_distinct_seed_lanes_diverge(self, ray_start_regular):
        """Two workers with different sample_seed_base must explore
        different trajectories from the same prompt (else GRPO sees
        zero-variance batches)."""
        outs = []
        for base in (0, 1_000_003):
            w = RolloutWorker(model="gpt", model_cfg=TINY, engine_config=ENG,
                              seed=0, sample_seed_base=base, warmup=False)
            try:
                w.submit([[1, 2, 3]], max_tokens=8, temperature=1.0)
                deadline = time.time() + 30
                trajs = []
                while not trajs and time.time() < deadline:
                    trajs = w.poll()["trajs"]
                    time.sleep(0.01)
                outs.append(trajs[0]["tokens"])
            finally:
                w.stop()
        assert outs[0] != outs[1]


# ---------------------------------------------------------------------------
# serve-hosted engines accept the same push path
# ---------------------------------------------------------------------------


@pytest.fixture
def serve_instance():
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


# tier-1 budget (ISSUE 20): 10.9s measured — the full serve-deployment swap
# rides slow; TestWeightSwap + test_apply_weight_update_engine_path keep the
# swap mechanics in tier-1 and the rlhf-smoke CI job runs this file in full
@pytest.mark.slow
def test_serve_deployment_update_weights(serve_instance, tiny_params):
    """One sync code path (rlhf.sync.apply_weight_update) for raw actor
    engines AND serve replicas: push a published WeightUpdate through the
    deployment handle, see the version land and generation continue —
    matching a fresh engine built from the pushed params."""
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_app

    handle = serve.run(
        build_llm_app(model="gpt", model_cfg=TINY, engine_config=ENG,
                      warmup=False),
        name="rlhf-push",
    )
    prompt = [1, 2, 3]
    before = handle.generate.remote(prompt, max_tokens=6).result(timeout=60)
    assert len(before) == 6
    assert handle.weights_version.remote().result(timeout=30) == 0

    other = gpt_init(jax.random.PRNGKey(9), TINY)
    update = publish_weights(other, 1)
    assert handle.update_weights.remote(update).result(timeout=60) == 1
    assert handle.weights_version.remote().result(timeout=30) == 1

    after = handle.generate.remote(prompt, max_tokens=6).result(timeout=60)
    ref_engine = LLMEngine(TINY, other, ENG)
    ref = ref_engine.generate(prompt, SamplingParams(max_tokens=6))
    assert after == ref


# ---------------------------------------------------------------------------
# the async loop end to end
# ---------------------------------------------------------------------------

TARGET = 7


def _reward(prompt, tokens):
    return sum(1 for t in tokens if t == TARGET) / max(len(tokens), 1)


# tier-1 budget (ISSUE 13): 19.7s measured on the dev box; the rlhf-smoke
# CI job runs this file's slow tier (plus the smoke module) on every push
@pytest.mark.slow
def test_async_loop_local_mode(ray_start_regular):
    """The whole loop minus actors (remote=False): poller stages, gate
    admits, learner updates, weights publish + apply, versions stamp."""
    cfg = RLHFConfig(
        model_cfg=TINY, engine_config=ENG,
        prompts=[[1, 2, 3]], reward_fn=_reward,
        num_rollout_workers=1, remote_rollouts=False, rollout_inflight=4,
        max_tokens=4, temperature=1.0, train_batch=4,
        buffer_capacity=8, lr=0.05, max_staleness=8, warmup=False,
        batch_timeout_s=60.0, seed=0,
    )
    algo = Algorithm(cfg)
    try:
        out = algo.train(3)
        real = [o for o in out if not o.get("skipped")]
        assert len(real) == 3
        assert algo.weights_version == 3
        assert algo.rollouts.versions() == [3]
        # late batches must contain post-push version stamps
        assert any(v > 0 for v in algo.stats()["last_batch_versions"])
        for o in real:
            assert o["trajectories"] == 4
            assert "learner/loss" in o
    finally:
        algo.shutdown()


@pytest.mark.slow
def test_async_rlhf_learns_with_overlap():
    """Acceptance: a tiny GPT policy trained via rlhf.Algorithm on a
    synthetic reward IMPROVES mean reward over N iterations while
    rollout and learner provably overlap (recorder events show
    rollout.finish timestamps interleaved with learner.step), weight
    pushes apply without draining, and trajectories carry correct
    weights_version stamps — the ray_tpu.rlhf.smoke run, asserted."""
    from ray_tpu.rlhf.smoke import run_smoke

    rec = run_smoke(iterations=12, num_workers=2, train_batch=16)
    assert rec["iterations"] >= 8, rec
    assert rec["overlapped"], rec
    assert rec["versions_advanced"], rec
    assert rec["improved"], rec
