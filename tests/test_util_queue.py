"""ray_tpu.util.queue tests (reference: ``python/ray/tests/test_queue.py``)."""

import threading

import pytest

import ray_tpu
from ray_tpu.util.queue import Empty, Full, Queue


def test_fifo_and_sizes(ray_start_regular):
    q = Queue()
    assert q.empty()
    for i in range(5):
        q.put(i)
    assert q.qsize() == 5 and not q.empty()
    assert [q.get() for _ in range(5)] == [0, 1, 2, 3, 4]
    q.shutdown()


def test_nowait_and_bounds(ray_start_regular):
    q = Queue(maxsize=2)
    q.put_nowait(1)
    q.put_nowait(2)
    assert q.full()
    with pytest.raises(Full):
        q.put_nowait(3)
    assert q.get_nowait() == 1
    q.shutdown()
    q2 = Queue()
    with pytest.raises(Empty):
        q2.get_nowait()
    q2.shutdown()


def test_blocking_get_with_timeout(ray_start_regular):
    q = Queue()
    with pytest.raises(Empty):
        q.get(timeout=0.5)
    q.shutdown()


def test_cross_task_producer_consumer(ray_start_regular):
    q = Queue(maxsize=8)

    @ray_tpu.remote
    def produce(q, n):
        for i in range(n):
            q.put(i)
        return n

    fut = produce.remote(q, 20)
    got = [q.get(timeout=30) for _ in range(20)]
    assert got == list(range(20))
    assert ray_tpu.get(fut, timeout=30) == 20
    q.shutdown()


def test_batch_ops(ray_start_regular):
    q = Queue()
    q.put_nowait_batch([1, 2, 3, 4])
    assert q.get_nowait_batch(3) == [1, 2, 3]
    assert q.get_nowait_batch(10) == [4]
    q.shutdown()
