"""Dashboard REST server (ray_tpu/dashboard.py).

Reference counterpart: the dashboard head's REST routes
(``dashboard/head.py`` + ``dashboard/modules/{node,actor,job,metrics}``) and
the Prometheus metrics agent (``dashboard/modules/reporter``).
"""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import dashboard


@pytest.fixture
def dash(ray_start_regular):
    url = dashboard.start(port=0)
    yield url
    dashboard.stop()


def _get(url, path):
    with urllib.request.urlopen(url + path, timeout=10) as r:
        ctype = r.headers.get("Content-Type", "")
        body = r.read()
    return ctype, body


def test_index_and_version(dash):
    ctype, body = _get(dash, "/")
    assert "text/html" in ctype and b"ray_tpu" in body
    _, body = _get(dash, "/api/version")
    assert json.loads(body)["dashboard"] == 1


def test_cluster_state_endpoints(dash):
    @ray_tpu.remote
    class Counter:
        def ping(self):
            return 1

    c = Counter.options(name="dash-counter").remote()
    ray_tpu.get(c.ping.remote())

    _, body = _get(dash, "/api/nodes")
    nodes = json.loads(body)
    assert len(nodes) >= 1

    _, body = _get(dash, "/api/actors")
    actors = json.loads(body)
    assert any(a.get("name") == "dash-counter" for a in actors)

    # live task table may already be drained; the timeline keeps history
    _, body = _get(dash, "/api/timeline")
    events = json.loads(body)
    assert any("dash-counter" in str(e.get("name")) for e in events)

    _, body = _get(dash, "/api/cluster_resources")
    res = json.loads(body)
    assert res["total"].get("CPU", 0) > 0

    _, body = _get(dash, "/api/summary")
    assert json.loads(body)


def test_prometheus_metrics_endpoint(dash):
    from ray_tpu.util.metrics import Counter as MCounter

    m = MCounter("dash_test_total", description="events")
    m.inc(3)
    from ray_tpu.util import metrics as um

    um.flush()
    ctype, body = _get(dash, "/metrics")
    assert "text/plain" in ctype
    assert b"dash_test_total" in body


def test_unknown_route_404(dash):
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as e:
        _get(dash, "/api/nope")
    assert e.value.code == 404
