"""Dashboard REST server (ray_tpu/dashboard.py).

Reference counterpart: the dashboard head's REST routes
(``dashboard/head.py`` + ``dashboard/modules/{node,actor,job,metrics}``) and
the Prometheus metrics agent (``dashboard/modules/reporter``).
"""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import dashboard


@pytest.fixture
def dash(ray_start_regular):
    url = dashboard.start(port=0)
    yield url
    dashboard.stop()


def _get(url, path):
    with urllib.request.urlopen(url + path, timeout=10) as r:
        ctype = r.headers.get("Content-Type", "")
        body = r.read()
    return ctype, body


def test_index_and_version(dash):
    ctype, body = _get(dash, "/")
    assert "text/html" in ctype and b"ray_tpu" in body
    _, body = _get(dash, "/api/version")
    assert json.loads(body)["dashboard"] == 1


def test_cluster_state_endpoints(dash):
    @ray_tpu.remote
    class Counter:
        def ping(self):
            return 1

    c = Counter.options(name="dash-counter").remote()
    ray_tpu.get(c.ping.remote())

    _, body = _get(dash, "/api/nodes")
    nodes = json.loads(body)
    assert len(nodes) >= 1

    _, body = _get(dash, "/api/actors")
    actors = json.loads(body)
    assert any(a.get("name") == "dash-counter" for a in actors)

    # live task table may already be drained; the timeline keeps history
    _, body = _get(dash, "/api/timeline")
    events = json.loads(body)
    assert any("dash-counter" in str(e.get("name")) for e in events)

    _, body = _get(dash, "/api/cluster_resources")
    res = json.loads(body)
    assert res["total"].get("CPU", 0) > 0

    _, body = _get(dash, "/api/summary")
    assert json.loads(body)


def test_prometheus_metrics_endpoint(dash):
    from ray_tpu.util.metrics import Counter as MCounter

    m = MCounter("dash_test_total", description="events")
    m.inc(3)
    from ray_tpu.util import metrics as um

    um.flush()
    ctype, body = _get(dash, "/metrics")
    assert "text/plain" in ctype
    assert b"dash_test_total" in body


def test_unknown_route_404(dash):
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as e:
        _get(dash, "/api/nope")
    assert e.value.code == 404


def test_static_spa_assets(dash):
    """The SPA is served from _dashboard_static/ (hand-written, no build)."""
    ctype, body = _get(dash, "/")
    assert "text/html" in ctype and b"/app.js" in body
    ctype, body = _get(dash, "/app.js")
    assert "javascript" in ctype
    # every state-API entity has a view in the app (VERDICT r4 #5)
    for needle in (b"nodes", b"actors", b"tasks", b"objects", b"placement_groups",
                   b"jobs", b"timeline", b"flamegraph", b"metrics", b"worker_stacks",
                   b"filterState"):
        assert needle in body, needle
    ctype, body = _get(dash, "/style.css")
    assert "css" in ctype and b"--accent" in body


def test_core_metrics_sampled(dash):
    """dashboard.start() launches the core-series sampler; /metrics then
    carries the runtime gauges the Grafana board charts."""
    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    from ray_tpu.util import metrics as um

    um.start_core_metrics(interval_s=0.2)
    import time

    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        um.flush()
        _, body = _get(dash, "/metrics")
        if b"ray_tpu_core_nodes" in body and b"ray_tpu_core_resource_total" in body:
            break
        time.sleep(0.3)
    assert b"ray_tpu_core_nodes" in body
    assert b"ray_tpu_core_resource_total" in body


def test_grafana_dashboard_json(dash):
    """Generated board imports cleanly: valid JSON with schemaVersion,
    templated prometheus datasource, and one panel per core series."""
    _, body = _get(dash, "/api/grafana")
    board = json.loads(body)
    assert board["uid"] and board["schemaVersion"] >= 30
    assert board["templating"]["list"][0]["type"] == "datasource"
    titles = [p["title"] for p in board["panels"]]
    assert "Tasks by state" in titles and "Alive nodes" in titles
    for p in board["panels"]:
        assert p["type"] == "timeseries"
        # exprs may wrap the series in PromQL functions (rate(),
        # histogram_quantile() — the LLM row), but always target our ns
        assert "ray_tpu_" in p["targets"][0]["expr"]
        assert "gridPos" in p and "id" in p

    # CLI writer round-trips
    import subprocess
    import sys
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json") as tf:
        r = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "grafana", "-o", tf.name],
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert r.returncode == 0, r.stderr
        with open(tf.name) as f:
            assert json.load(f)["uid"] == board["uid"]


def test_logs_endpoint_shape(dash):
    _, body = _get(dash, "/api/logs?job_id=nope")
    data = json.loads(body)
    assert "logs" in data and data["job_id"] == "nope"


def test_observability_endpoints(dash):
    """PR 4 surfaces: /api/percentiles, /api/events (+ filters),
    /api/request — the HTTP face of obs top / obs events / obs req."""
    from ray_tpu._private import events
    from ray_tpu.util import metrics as um
    from ray_tpu.util.metrics import Histogram

    h = Histogram("dash_lat_s", "latency", boundaries=(0.1, 1.0))
    for v in (0.05, 0.5, 2.0):
        h.observe(v)
    um.flush()
    _, body = _get(dash, "/api/percentiles")
    pcts = json.loads(body)
    snap = next(iter(pcts["dash_lat_s"].values()))
    assert snap["count"] == 3 and snap["p50"] > 0

    events.record("dash.test_event", request_id="dash-rid-1", n=7)
    events.record("dash.other")
    _, body = _get(dash, "/api/events?tail=50")
    evs = json.loads(body)
    assert any(e["type"] == "dash.test_event" for e in evs)
    _, body = _get(dash, "/api/events?request_id=dash-rid-1")
    only = json.loads(body)
    assert only and all(e.get("request_id") == "dash-rid-1" for e in only)

    _, body = _get(dash, "/api/request?id=dash-rid-1")
    req = json.loads(body)
    assert any(e["type"] == "dash.test_event" and e["n"] == 7 for e in req)
    _, body = _get(dash, "/api/request")
    assert "error" in json.loads(body)
