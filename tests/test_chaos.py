"""Chaos suite: randomized worker SIGKILLs under live workloads.

Reference: ``python/ray/tests/test_chaos.py`` +
``_private/test_utils.py:1396`` (ResourceKillerActor). Every kill must be
absorbed by task retries, the actor restart FSM, or lineage reconstruction
— a wrong result, lost object, or hang is a bug. Seeds are fixed so a
failure reproduces.
"""

import time

import pytest

import ray_tpu
from ray_tpu._private.chaos import ResourceKiller


@pytest.fixture
def chaos_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


@pytest.mark.parametrize("seed", [1, 2])
def test_tasks_survive_worker_kills(chaos_cluster, seed):
    @ray_tpu.remote(max_retries=-1)
    def sq(x):
        time.sleep(0.02)
        return x * x

    with ResourceKiller(interval_s=0.4, seed=seed, max_kills=6) as killer:
        refs = [sq.remote(i) for i in range(200)]
        out = ray_tpu.get(refs, timeout=180)
    assert out == [i * i for i in range(200)]
    assert killer.kills, "killer never fired — the test exercised nothing"


def test_actors_survive_worker_kills(chaos_cluster):
    @ray_tpu.remote(max_restarts=-1, max_task_retries=-1)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            time.sleep(0.01)
            return self.n

    actors = [Counter.remote() for _ in range(4)]
    with ResourceKiller(interval_s=0.2, seed=3, max_kills=4) as killer:
        results = []
        # keep rounds coming until the killer has actually fired (the warm
        # worker pool made actor creation+calls so fast that a fixed round
        # count can outrun the first kill tick entirely)
        deadline = time.monotonic() + 60
        round_i = 0
        while round_i < 10 or (not killer.kills and time.monotonic() < deadline):
            # liveness bound, not latency: under full-suite CPU starvation a
            # kill->respawn->retry cycle can legitimately take minutes on a
            # 1-core box (observed once in 479 at timeout=120)
            results.append(ray_tpu.get([a.bump.remote() for a in actors], timeout=240))
            round_i += 1
    # counts are monotone per actor; restarts may reset state (fresh
    # __init__) but every CALL must succeed — the invariant is liveness +
    # per-round success, not cross-restart state (reference semantics)
    assert all(len(r) == 4 for r in results)
    assert killer.kills


def test_lineage_reconstruction_under_kills(chaos_cluster):
    """Objects produced by killed workers must be reconstructable when the
    shm backing is gone (owner re-executes the creating task)."""

    @ray_tpu.remote(max_retries=-1)
    def make_block(i):
        import numpy as np

        return np.full((1 << 16,), i, dtype=np.int64)  # 512KB: shm path

    @ray_tpu.remote(max_retries=-1)
    def reduce_block(b):
        return int(b[0]) * 2

    with ResourceKiller(interval_s=0.4, seed=5, max_kills=5) as killer:
        blocks = [make_block.remote(i) for i in range(40)]
        outs = ray_tpu.get([reduce_block.remote(b) for b in blocks], timeout=180)
    assert outs == [i * 2 for i in range(40)]
    assert killer.kills


def test_data_pipeline_under_kills(chaos_cluster):
    import ray_tpu.data as rdata

    with ResourceKiller(interval_s=0.5, seed=8, max_kills=4) as killer:
        ds = rdata.range(400, parallelism=16).map(lambda r: {"v": r["id"] * 3})
        total = sum(r["v"] for r in ds.take_all())
    assert total == 3 * sum(range(400))
    assert killer.kills
