"""ray_tpu.tune tests — mirror the reference's tune test strategy: variant
generation, trial execution, ASHA early stopping, PBT exploit/explore,
checkpoint resume, failure retries, ResultGrid."""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import Checkpoint, FailureConfig, RunConfig


@pytest.fixture
def storage(tmp_path):
    return str(tmp_path / "tune_results")


def test_variant_generation():
    gen = tune.BasicVariantGenerator(seed=0)
    cfgs = gen.generate(
        {"lr": tune.grid_search([0.1, 0.2]), "wd": tune.uniform(0, 1), "c": 5},
        num_samples=3,
    )
    assert len(cfgs) == 6
    assert {c["lr"] for c in cfgs} == {0.1, 0.2}
    assert all(0 <= c["wd"] <= 1 and c["c"] == 5 for c in cfgs)


def test_nested_space_and_choice():
    gen = tune.BasicVariantGenerator(seed=1)
    cfgs = gen.generate({"opt": {"lr": tune.choice([1, 2]), "name": "adam"}}, num_samples=4)
    assert len(cfgs) == 4
    assert all(c["opt"]["lr"] in (1, 2) and c["opt"]["name"] == "adam" for c in cfgs)


def test_basic_tune_run(ray_start_regular, storage):
    def trainable(config):
        score = (config["x"] - 3) ** 2
        tune.report({"score": score})

    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([0, 1, 2, 3, 4])},
        tune_config=tune.TuneConfig(metric="score", mode="min"),
        run_config=RunConfig(name="basic", storage_path=storage),
    ).fit()
    assert len(grid) == 5
    best = grid.get_best_result()
    assert best.metrics["score"] == 0


def test_asha_early_stops(ray_start_regular, storage):
    def trainable(config):
        for i in range(8):
            # bad configs plateau high; good ones descend
            loss = config["base"] - i * 0.5 if config["base"] < 5 else config["base"]
            tune.report({"loss": loss})

    grid = tune.run(
        trainable,
        config={"base": tune.grid_search([1.0, 2.0, 10.0, 12.0])},
        metric="loss",
        mode="min",
        scheduler=tune.ASHAScheduler(metric="loss", mode="min", grace_period=1, max_t=8, reduction_factor=2),
        storage_path=storage,
        name="asha",
    )
    iters = {r.metrics["trial_id"]: r.metrics["training_iteration"] for r in grid}
    assert len(grid) == 4
    # the bad trials must not run all 8 iterations
    stopped_early = [v for v in iters.values() if v < 8]
    assert stopped_early, iters


def test_pbt_exploits_checkpoint(ray_start_regular, storage):
    def trainable(config):
        import tempfile

        ckpt = tune.get_checkpoint()
        level = 0.0
        if ckpt is not None:
            with ckpt.as_directory() as d:
                with open(os.path.join(d, "lvl")) as f:
                    level = float(f.read())
        for i in range(6):
            level += config["rate"]
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "lvl"), "w") as f:
                f.write(str(level))
            tune.report({"reward": level}, checkpoint=Checkpoint.from_directory(d))

    pbt = tune.PopulationBasedTraining(
        metric="reward",
        mode="max",
        perturbation_interval=2,
        hyperparam_mutations={"rate": tune.uniform(0.1, 2.0)},
        seed=0,
    )
    grid = tune.run(
        trainable,
        config={"rate": tune.grid_search([0.1, 2.0])},
        metric="reward",
        mode="max",
        scheduler=pbt,
        storage_path=storage,
        name="pbt",
    )
    best = grid.get_best_result()
    assert best.metrics["reward"] > 2.0  # high-rate path dominates


def test_trial_failure_retry(ray_start_regular, storage, tmp_path):
    marker = str(tmp_path / "crashed_once")

    def trainable(config):
        if not os.path.exists(marker):
            with open(marker, "w") as f:
                f.write("1")
            os._exit(1)
        tune.report({"ok": 1})

    grid = tune.Tuner(
        trainable,
        param_space={},
        tune_config=tune.TuneConfig(metric="ok", mode="max"),
        run_config=RunConfig(
            name="retry", storage_path=storage, failure_config=FailureConfig(max_failures=1)
        ),
    ).fit()
    assert grid[0].error is None
    assert grid[0].metrics["ok"] == 1


def test_experiment_state_written(ray_start_regular, storage):
    def trainable(config):
        tune.report({"m": config["x"]})

    tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        run_config=RunConfig(name="state", storage_path=storage),
    ).fit()
    state_file = os.path.join(storage, "state", "experiment_state.json")
    assert os.path.exists(state_file)
    import json

    state = json.load(open(state_file))
    assert len(state["trials"]) == 2
    assert all(t["state"] == "TERMINATED" for t in state["trials"])


def test_trainer_in_tuner(ray_start_regular, storage):
    """Reference: BaseTrainer.fit runs as a 1-trial Tune experiment; ours
    composes the other way — a Trainer is tunable via as_trainable()."""
    from ray_tpu.train import JaxTrainer, ScalingConfig

    def loop(config):
        from ray_tpu import train

        train.report({"val": config["v"] * 2})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="inner", storage_path=storage),
    )
    grid = tune.Tuner(
        trainer,
        param_space={"v": tune.grid_search([1, 5])},
        tune_config=tune.TuneConfig(metric="val", mode="max"),
        run_config=RunConfig(name="outer", storage_path=storage),
    ).fit()
    assert grid.get_best_result().metrics["val"] == 10
