"""Zero-noise teardown (VERDICT r4 #10): a driver that exits — cleanly or
abruptly — must leave NOTHING on stderr. The reference's worker teardown is
silent by design (``python/ray/_private/worker.py`` main_loop); tracebacks
from late-spawning workers mask real shm-lifetime bugs.

These scenarios pin the historical noise sources: shutdown with spawns
mid-flight (register hits a dead head), shutdown with results mid-send
(task_done hits a closed socket), and a dirty ``os._exit`` driver. Each
subprocess's stderr is read to EOF, which by construction waits for every
orphaned worker holding the fd — late prints cannot escape the assertion.
"""

import os
import subprocess
import sys

import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ENV = dict(os.environ, PALLAS_AXON_POOL_IPS="", PYTHONPATH=_REPO_ROOT)

SCENARIOS = {
    "shutdown_mid_spawn": """
import ray_tpu
ray_tpu.init(num_cpus=8)

@ray_tpu.remote
def f(x):
    return x

refs = [f.remote(i) for i in range(16)]
ray_tpu.shutdown()   # immediately: workers mid-fork/registration
""",
    "shutdown_mid_result": """
import time
import ray_tpu
ray_tpu.init(num_cpus=4)

@ray_tpu.remote
def slow(x):
    time.sleep(0.3)
    return bytes(200_000)  # result in flight when the head dies

refs = [slow.remote(i) for i in range(8)]
time.sleep(0.35)
ray_tpu.shutdown()
""",
    "dirty_exit_with_actors": """
import os, time
import ray_tpu
ray_tpu.init(num_cpus=4)

@ray_tpu.remote(num_cpus=0)
class A:
    def ping(self):
        return 1

actors = [A.remote() for _ in range(4)]
refs = [a.ping.remote() for a in actors]
time.sleep(0.1)
os._exit(0)  # no shutdown, no atexit
""",
}


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_teardown_is_silent(name):
    r = subprocess.run(
        [sys.executable, "-c", SCENARIOS[name]],
        capture_output=True,
        text=True,
        timeout=180,
        env=_ENV,
    )
    assert "Traceback" not in r.stderr, f"{name} stderr:\n{r.stderr[:2000]}"
    assert "Error" not in r.stderr, f"{name} stderr:\n{r.stderr[:2000]}"
