"""Serve hardening (VERDICT r2 #8): per-node proxies, streaming responses
over streaming-generator returns, long-poll config push (no router
polling), non-JSON bodies.

Reference: ``serve/_private/proxy.py:759`` (streaming ASGI responses, one
proxy per node), ``serve/_private/long_poll.py`` (LongPollHost pushing
config to routers)."""

import http.client
import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_instance():
    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_streaming_http_endpoint(serve_instance):
    @serve.deployment
    def tokens(payload):
        n = (payload or {}).get("n", 3)
        for i in range(n):
            time.sleep(0.5)
            yield {"token": i}

    serve.run(tokens.bind(), name="stream", http=True, http_port=0)
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    port = ray_tpu.get(controller.get_proxy_port.remote(), timeout=30)

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    t0 = time.monotonic()
    conn.request(
        "POST", "/stream", body=json.dumps({"n": 4}),
        headers={"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    assert resp.status == 200
    first = resp.readline()  # HTTPResponse de-chunks transparently
    t_first = time.monotonic() - t0
    items = [json.loads(first)]
    for line in resp:
        if line.strip():
            items.append(json.loads(line))
    t_all = time.monotonic() - t0
    conn.close()
    assert items == [{"token": i} for i in range(4)]
    # the first chunk must arrive while the producer is still generating
    assert t_first < t_all - 1.0, (t_first, t_all)


def test_streaming_handle(serve_instance):
    @serve.deployment
    class Gen:
        def __call__(self, n):
            for i in range(n):
                yield i * 2

    handle = serve.run(Gen.bind(), name="genapp", http=False)
    out = list(handle.options(stream=True).remote(5))
    assert out == [0, 2, 4, 6, 8]


def test_non_json_bodies(serve_instance):
    @serve.deployment
    class Bytes:
        def __call__(self, payload):
            assert isinstance(payload, bytes)
            return payload[::-1]  # bytes in, bytes out

    serve.run(Bytes.bind(), name="raw", http=True, http_port=0)
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    port = ray_tpu.get(controller.get_proxy_port.remote(), timeout=30)

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/raw",
        data=b"\x00\x01binary\xff",
        headers={"Content-Type": "application/octet-stream"},
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        assert resp.headers.get("Content-Type") == "application/octet-stream"
        assert resp.read() == b"\x00\x01binary\xff"[::-1]


def test_no_steady_state_polling(serve_instance):
    """Routers get config PUSHED via the controller long-poll: after warmup,
    serving requests must not add a single get_replicas pull."""

    @serve.deployment
    def double(x):
        return x * 2

    handle = serve.run(double.bind(), name="lp", http=False)
    assert handle.remote(4).result(timeout=30) == 8  # warm the router
    time.sleep(1.0)  # let any startup pulls settle
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    before = ray_tpu.get(controller.get_pull_count.remote(), timeout=30)
    for i in range(25):
        assert handle.remote(i).result(timeout=30) == 2 * i
    after = ray_tpu.get(controller.get_pull_count.remote(), timeout=30)
    assert after == before, f"routers pulled {after - before} times in steady state"


def test_per_node_proxies_and_failover(serve_instance):
    """One proxy per alive node; with a node (and its proxy) gone, the
    surviving node's proxy still serves."""
    from ray_tpu._private.runtime import get_ctx

    head = get_ctx().head
    node2 = head.add_node({"CPU": 4.0})

    @serve.deployment(num_replicas=2)
    def ping(x):
        return {"pong": x}

    serve.run(ping.bind(), name="ha", http=True, http_port=0)
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")

    deadline = time.monotonic() + 30
    ports = {}
    while time.monotonic() < deadline:
        ports = ray_tpu.get(controller.get_proxy_ports.remote(), timeout=30)
        if len(ports) >= 2:
            break
        time.sleep(0.25)
    assert len(ports) >= 2, f"expected a proxy per node, got {ports}"

    def get_via(port, i):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/ha",
            data=json.dumps(i).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())

    for port in ports.values():
        assert get_via(port, 7) == {"pong": 7}

    # kill node 2: its proxy (and any replicas there) die with it
    head.remove_node(node2)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        left = ray_tpu.get(controller.get_proxy_ports.remote(), timeout=30)
        if node2.binary().hex() not in left:
            break
        time.sleep(0.25)
    survivor_ports = ray_tpu.get(controller.get_proxy_ports.remote(), timeout=30)
    assert survivor_ports, "no proxy survived"
    deadline = time.monotonic() + 60
    while True:
        try:
            assert get_via(list(survivor_ports.values())[0], 9) == {"pong": 9}
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
