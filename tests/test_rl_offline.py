"""Offline RL: dataset IO, BC learning from scripted expert data, CQL
conservatism.

Reference counterparts: ``rllib/offline/`` (experience IO),
``rllib/algorithms/bc``, ``rllib/algorithms/cql``.
"""

import numpy as np
import pytest

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.offline import OfflineDataset, record_experience


def _expert_cartpole(obs):
    """Scripted balancer: push in the direction the pole is falling. Keeps
    CartPole up for hundreds of steps — good-enough expert for BC."""
    angle, ang_vel = obs[2], obs[3]
    return 1 if (angle + 0.5 * ang_vel) > 0 else 0


class TestOfflineDataset:
    def test_record_and_sample(self):
        ds = record_experience("CartPole-v1", 500, policy=_expert_cartpole, seed=1)
        assert len(ds) == 500
        b = ds.sample(64)
        assert b[sb.OBS].shape == (64, 4)
        assert set(np.unique(b[sb.ACTIONS])) <= {0, 1}

    def test_npz_roundtrip(self, tmp_path):
        ds = record_experience("CartPole-v1", 100, seed=2)
        p = ds.save_npz(str(tmp_path / "exp.npz"))
        back = OfflineDataset.from_npz(p)
        assert len(back) == 100
        np.testing.assert_array_equal(back.columns[sb.OBS], ds.columns[sb.OBS])

    def test_jsonl_import(self, tmp_path):
        import json

        p = tmp_path / "exp.jsonl"
        with open(p, "w") as f:
            for i in range(10):
                f.write(
                    json.dumps(
                        {
                            "obs": [float(i)] * 4,
                            "actions": i % 2,
                            "rewards": 1.0,
                            "next_obs": [float(i + 1)] * 4,
                            "terminateds": False,
                        }
                    )
                    + "\n"
                )
        ds = OfflineDataset.from_jsonl(str(p))
        assert len(ds) == 10 and ds.columns[sb.OBS].shape == (10, 4)


class TestBC:
    def test_bc_clones_expert(self):
        """BC on scripted-expert CartPole data reaches good returns without
        ever training in the env (the offline-RL acceptance test, mirroring
        rllib's BC learning tests)."""
        from ray_tpu.rl.algorithms.bc import BCConfig

        data = record_experience("CartPole-v1", 4000, policy=_expert_cartpole, seed=3)
        algo = (
            BCConfig()
            .environment("CartPole-v1")
            .training(
                offline_data=data,
                lr=3e-3,
                updates_per_iter=150,
                train_batch_size=256,
                evaluation_steps=1200,
            )
            .debugging(seed=0)
            .build()
        )
        best = 0.0
        for _ in range(8):
            res = algo.train()
            ret = res.get("episode_return_mean")
            if ret is not None:
                best = max(best, ret)
            if best >= 120.0:
                break
        assert best >= 120.0, f"BC failed to clone the expert (best={best})"

    def test_bc_requires_data(self):
        from ray_tpu.rl.algorithms.bc import BCConfig

        with pytest.raises(ValueError, match="offline_data"):
            BCConfig().environment("CartPole-v1").build()


class TestCQL:
    def _pendulum_data(self, n=1500):
        return record_experience("Pendulum-v1", n, seed=4)

    def test_cql_runs_and_penalty_reported(self):
        from ray_tpu.rl.algorithms.cql import CQLConfig

        algo = (
            CQLConfig()
            .environment("Pendulum-v1")
            .training(
                offline_data=self._pendulum_data(),
                updates_per_iter=20,
                train_batch_size=64,
                cql_alpha=1.0,
            )
            .debugging(seed=0)
            .build()
        )
        res = algo.train()
        assert "learner/cql_penalty" in res
        assert np.isfinite(res["learner/cql_penalty"])

    # tier1-durations: ~31s on the CI box — the full suite overruns the
    # 870s tier-1 budget (truncation, not failures; ROADMAP), so the heaviest
    # non-LLM learning/scale tests run as @slow instead of being cut at random
    @pytest.mark.slow
    def test_cql_is_more_conservative_than_sac(self):
        """The defining CQL property: the penalty shrinks the gap between
        Q on out-of-distribution (policy/random) actions and Q on dataset
        actions — extrapolated Q cannot sit above the data. Compare the
        trained OOD-vs-data Q gap with and without the penalty."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl.algorithms.cql import CQLConfig

        data = self._pendulum_data()
        obs = jnp.asarray(data.columns[sb.OBS][:256])
        acts = jnp.asarray(data.columns[sb.ACTIONS][:256]).reshape(256, -1)

        def ood_gap(cql_alpha):
            algo = (
                CQLConfig()
                .environment("Pendulum-v1")
                .training(
                    offline_data=data,
                    updates_per_iter=100,
                    train_batch_size=128,
                    cql_alpha=cql_alpha,
                )
                .debugging(seed=0)
                .build()
            )
            for _ in range(2):
                algo.train()
            params = algo.get_weights()
            from ray_tpu.rl.algorithms.sac import SACModule
            from ray_tpu.rl.rl_module import RLModuleSpec

            obs_space, act_space = algo.foreach_runner("get_spaces")[0]
            m = SACModule(RLModuleSpec(obs_space, act_space, hidden=(64, 64)))
            a, _ = m.sample_action_logp(params, obs, jax.random.PRNGKey(9))
            q1o, q2o = m.q_values(params, obs, a)
            q1d, q2d = m.q_values(params, obs, acts)
            return float(
                (jnp.minimum(q1o, q2o) - jnp.minimum(q1d, q2d)).mean()
            )

        assert ood_gap(10.0) < ood_gap(0.0), (
            "CQL penalty should depress OOD Q relative to dataset Q"
        )
