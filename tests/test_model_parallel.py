"""Compute-stack tests on the 8-device virtual CPU mesh: GPT model,
sharding rules, compiled SPMD train step (dp/fsdp/tp)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models.gpt import GPTConfig, gpt_forward, gpt_init, gpt_loss
from ray_tpu.parallel.mesh import MeshConfig, make_mesh
from ray_tpu.parallel.sharding import batch_spec, param_sharding_rules
from ray_tpu.parallel.train_step import build_train_step

TINY = GPTConfig(vocab_size=256, seq_len=64, d_model=64, n_layers=2, n_heads=4, dtype="float32")


def test_mesh_factoring():
    m = make_mesh(MeshConfig(dp=-1, fsdp=2, tp=2), devices=jax.devices("cpu")[:8])
    assert dict(zip(m.axis_names, m.devices.shape)) == {"dp": 2, "fsdp": 2, "ep": 1, "tp": 2, "sp": 1}
    with pytest.raises(ValueError):
        MeshConfig(dp=3, fsdp=1, tp=1).resolve(8)
    with pytest.raises(ValueError):
        MeshConfig(dp=-1, fsdp=-1).resolve(8)


def test_forward_shapes():
    params = gpt_init(jax.random.PRNGKey(0), TINY)
    tokens = jnp.zeros((2, TINY.seq_len), jnp.int32)
    logits = jax.jit(lambda p, t: gpt_forward(TINY, p, t))(params, tokens)
    assert logits.shape == (2, TINY.seq_len, TINY.vocab_size)
    assert logits.dtype == jnp.float32


def test_causality():
    # logits at position i must not depend on tokens after i
    params = gpt_init(jax.random.PRNGKey(0), TINY)
    t1 = jax.random.randint(jax.random.PRNGKey(1), (1, TINY.seq_len), 0, 256, jnp.int32)
    t2 = t1.at[0, -1].set((t1[0, -1] + 1) % 256)
    l1 = gpt_forward(TINY, params, t1)
    l2 = gpt_forward(TINY, params, t2)
    np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], atol=1e-5)
    assert not np.allclose(l1[0, -1], l2[0, -1])


def test_sharding_rules_cover_all_params():
    # every spec must be rank-compatible with its parameter
    params = gpt_init(jax.random.PRNGKey(0), TINY)
    specs = param_sharding_rules(params)
    flat_p = jax.tree_util.tree_leaves(params)
    flat_s = jax.tree_util.tree_leaves(specs, is_leaf=lambda x: not isinstance(x, dict))
    assert len(flat_p) == len(flat_s)
    for p, s in zip(flat_p, flat_s):
        assert len(s) <= p.ndim, f"spec {s} too long for shape {p.shape}"


# tier-1 budget (ISSUE 20): 8.3s/axes measured (x3 params) — the training
# loops ride slow; test_parallelism_modes_agree keeps cross-mode parity and
# test_sharding_rules_cover_all_params keeps the sharding contract in tier-1
@pytest.mark.slow
@pytest.mark.parametrize("axes", [dict(dp=8, fsdp=1, tp=1), dict(dp=2, fsdp=2, tp=2), dict(dp=1, fsdp=4, tp=2)])
def test_train_step_loss_decreases(axes):
    mesh = make_mesh(MeshConfig(sp=1, **axes), devices=jax.devices("cpu")[:8])

    def loss_fn(params, batch):
        return gpt_loss(TINY, params, batch, mesh)

    init_fn, step_fn = build_train_step(loss_fn, optax.adamw(1e-2), mesh)
    state = init_fn(gpt_init(jax.random.PRNGKey(0), TINY))

    from jax.sharding import NamedSharding

    batch = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (8, TINY.seq_len + 1), 0, 256, jnp.int32),
        NamedSharding(mesh, batch_spec()),
    )
    losses = []
    for _ in range(5):
        state, loss = step_fn(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], f"no learning on a fixed batch: {losses}"


def test_parallelism_modes_agree():
    # dp-only vs dp×fsdp×tp must produce (numerically close) identical steps
    results = {}
    for name, axes in {"dp": dict(dp=8, fsdp=1, tp=1), "3d": dict(dp=2, fsdp=2, tp=2)}.items():
        mesh = make_mesh(MeshConfig(sp=1, **axes), devices=jax.devices("cpu")[:8])

        def loss_fn(params, batch, mesh=mesh):
            return gpt_loss(TINY, params, batch, mesh)

        init_fn, step_fn = build_train_step(loss_fn, optax.sgd(0.1), mesh)
        state = init_fn(gpt_init(jax.random.PRNGKey(0), TINY))
        from jax.sharding import NamedSharding

        batch = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (8, TINY.seq_len + 1), 0, 256, jnp.int32),
            NamedSharding(mesh, batch_spec()),
        )
        state, loss = step_fn(state, batch)
        results[name] = float(loss)
    assert abs(results["dp"] - results["3d"]) < 1e-4, results
