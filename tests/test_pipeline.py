"""Pipeline parallelism (pp axis) tests: GPipe microbatching over ppermute
must match sequential layer application, forward and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from ray_tpu.parallel.pipeline import pipeline_apply


def _mesh(pp, extra=1):
    devs = np.array(jax.devices()[: pp * extra]).reshape(extra, pp)
    return Mesh(devs, (("dp", "pp") if extra > 1 else ("x", "pp"))[-2:])


def _layers(n_layers, d, key):
    ks = jax.random.split(key, n_layers)
    return {
        "w": jnp.stack([jax.random.normal(k, (d, d)) * (d**-0.5) for k in ks]),
        "b": jnp.zeros((n_layers, d)),
    }


def _stage_fn(params, x):
    def body(h, layer):
        return jnp.tanh(h @ layer["w"] + layer["b"]), None

    out, _ = jax.lax.scan(body, x, params)
    return out


def _sequential(params, x):
    return _stage_fn(params, x)


@pytest.mark.parametrize("pp,microbatches", [(2, 2), (2, 4), (4, 4)])
def test_pipeline_matches_sequential(pp, microbatches):
    d, n_layers, batch = 16, 8, 8
    key = jax.random.PRNGKey(0)
    params = _layers(n_layers, d, key)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))
    ref = _sequential(params, x)

    devs = np.array(jax.devices()[:pp]).reshape(pp)
    mesh = Mesh(devs, ("pp",))
    with mesh:
        out = jax.jit(
            lambda p, x: pipeline_apply(
                _stage_fn, p, x, mesh, n_layers, microbatches, batch_axes=()
            )
        )(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match_sequential():
    d, n_layers, batch = 8, 4, 4
    params = _layers(n_layers, d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (batch, d))

    def ref_loss(p):
        return ((_sequential(p, x) - tgt) ** 2).mean()

    devs = np.array(jax.devices()[:2]).reshape(2)
    mesh = Mesh(devs, ("pp",))

    def pp_loss(p):
        out = pipeline_apply(_stage_fn, p, x, mesh, n_layers, 2, batch_axes=())
        return ((out - tgt) ** 2).mean()

    g_ref = jax.grad(ref_loss)(params)
    with mesh:
        g_pp = jax.jit(jax.grad(pp_loss))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_ref), jax.tree_util.tree_leaves(g_pp)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-5, rtol=1e-5)


def test_pipeline_with_batch_sharding():
    """pp=2 combined with dp=2: batch sharded over dp, layers over pp."""
    d, n_layers, batch = 8, 4, 8
    params = _layers(n_layers, d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))
    ref = _sequential(params, x)
    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("dp", "pp"))
    with mesh:
        out = jax.jit(
            lambda p, x: pipeline_apply(
                _stage_fn, p, x, mesh, n_layers, 2, batch_axes=(("dp",),)
            )
        )(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_pipeline_pp1_passthrough():
    d, n_layers, batch = 8, 4, 4
    params = _layers(n_layers, d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))
    devs = np.array(jax.devices()[:2]).reshape(2)
    mesh = Mesh(devs, ("dp",))  # no pp axis
    out = pipeline_apply(_stage_fn, params, x, mesh, n_layers, 2, batch_axes=())
    np.testing.assert_allclose(np.asarray(out), np.asarray(_sequential(params, x)))
