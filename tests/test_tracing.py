"""User-span tracing merged with runtime task events.

Reference: ``python/ray/util/tracing/tracing_helper.py`` + ``ray timeline``.
"""

import ray_tpu
from ray_tpu.util import tracing


def test_span_records_duration_and_attrs():
    tracing.clear()
    with tracing.span("outer", phase=1):
        with tracing.span("inner"):
            pass
    spans = tracing.get_spans()
    names = [s["name"] for s in spans]
    assert names == ["inner", "outer"]  # children finish first
    outer = spans[1]
    assert outer["dur"] >= spans[0]["dur"]
    assert outer["args"]["phase"] == 1
    tracing.clear()


def test_export_merges_task_events_and_user_spans(ray_start_regular, tmp_path):
    tracing.clear()

    @ray_tpu.remote
    def traced_task():
        from ray_tpu.util import tracing as t

        with t.span("in-task-work"):
            return 1

    with tracing.span("driver-section"):
        assert ray_tpu.get(traced_task.remote(), timeout=60) == 1

    out = str(tmp_path / "trace.json")
    events = tracing.export_chrome_trace(out)
    import json

    with open(out) as f:
        loaded = json.load(f)
    assert loaded == events
    names = {e["name"] for e in events}
    assert "driver-section" in names
    assert any("traced_task" in n for n in names)  # runtime task event
    # chrome trace shape
    assert all({"ph", "ts", "pid"} <= set(e) for e in events)
    tracing.clear()
