"""Searcher plugin API + TPE tests (reference:
``tune/tests/test_searchers.py`` themes)."""

import math

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.searcher import (
    FINISHED,
    ConcurrencyLimiter,
    RandomSearcher,
    Searcher,
    TPESearcher,
)


def test_custom_searcher_plugs_into_tuner(ray_start_regular):
    """A user-defined Searcher drives trial configs sequentially and sees
    completions."""

    class FixedSearcher(Searcher):
        def __init__(self):
            super().__init__(metric="score", mode="min")
            self.suggested = []
            self.completed = []

        def suggest(self, trial_id):
            if len(self.suggested) >= 4:
                return FINISHED
            cfg = {"x": len(self.suggested)}
            self.suggested.append(trial_id)
            return cfg

        def on_trial_complete(self, trial_id, result=None, error=False):
            self.completed.append((trial_id, result["score"] if result else None, error))

    def trainable(config):
        tune.report({"score": config["x"] ** 2})

    searcher = FixedSearcher()
    grid = tune.Tuner(
        trainable,
        tune_config=tune.TuneConfig(
            metric="score", mode="min", num_samples=10, search_alg=searcher,
            max_concurrent_trials=2,
        ),
    ).fit()
    # FINISHED capped it at 4 despite num_samples=10
    assert len(grid) == 4
    assert len(searcher.completed) == 4
    assert {r.metrics["score"] for r in grid} == {0, 1, 4, 9}
    assert grid.get_best_result().metrics["score"] == 0


def test_tpe_unit_beats_random_on_quadratic():
    """TPE must concentrate samples near the optimum of a smooth function
    faster than pure random sampling (seeded, deterministic)."""

    def run_searcher(searcher, n=60):
        searcher.set_search_properties("loss", "min", {"x": tune.uniform(-10, 10)})
        best = math.inf
        for i in range(n):
            cfg = searcher.suggest(f"t{i}")
            loss = (cfg["x"] - 3.0) ** 2
            best = min(best, loss)
            searcher.on_trial_complete(f"t{i}", {"loss": loss})
        return best

    tpe_best = run_searcher(TPESearcher(metric="loss", mode="min", n_initial=10, seed=0))
    rnd_best = run_searcher(RandomSearcher(metric="loss", mode="min", seed=0))
    assert tpe_best < 0.05, f"TPE did not converge: best={tpe_best}"
    assert tpe_best <= rnd_best


def test_tpe_categorical_and_mode_max():
    searcher = TPESearcher(metric="acc", mode="max", n_initial=6, seed=1)
    searcher.set_search_properties(
        "acc", "max", {"opt": tune.choice(["bad", "ok", "good"]), "lr": tune.loguniform(1e-4, 1e-1)}
    )
    payoff = {"bad": 0.1, "ok": 0.5, "good": 0.9}
    picks = []
    for i in range(40):
        cfg = searcher.suggest(f"t{i}")
        acc = payoff[cfg["opt"]] - abs(math.log10(cfg["lr"]) + 2) * 0.01
        picks.append(cfg["opt"])
        searcher.on_trial_complete(f"t{i}", {"acc": acc})
    # after warmup TPE should prefer 'good'
    tail = picks[20:]
    assert tail.count("good") > len(tail) * 0.5, tail


def test_concurrency_limiter_caps_inflight():
    inner = RandomSearcher(metric="m", mode="min", seed=0)
    lim = ConcurrencyLimiter(inner, max_concurrent=2)
    lim.set_search_properties("m", "min", {"x": tune.uniform(0, 1)})
    a = lim.suggest("a")
    b = lim.suggest("b")
    assert isinstance(a, dict) and isinstance(b, dict)
    assert lim.suggest("c") is None  # at cap
    lim.on_trial_complete("a", {"m": 1.0})
    assert isinstance(lim.suggest("c"), dict)


def test_tpe_in_tuner_end_to_end(ray_start_regular):
    def trainable(config):
        tune.report({"loss": (config["x"] - 2) ** 2 + config["y"]})

    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.uniform(-5, 5), "y": tune.choice([0.0, 1.0])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=20,
            search_alg=TPESearcher(n_initial=6, seed=0), max_concurrent_trials=4,
        ),
    ).fit()
    assert len(grid) == 20
    best = grid.get_best_result()
    # trial COMPLETION order (and so TPE's observation sequence) varies with
    # scheduling; the bound must hold for any order — random search on this
    # space averages ~2.5+, TPE lands well under with margin
    assert best.metrics["loss"] < 2.5
