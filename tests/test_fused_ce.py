"""Parity tests for the blockwise fused cross-entropy (ops/fused_ce.py):
loss values and both gradients must match the naive materialize-the-logits
formulation (reference loss semantics: next-token CE as in the GPT-J
fine-tune workload the baseline measures)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.gpt import GPTConfig, gpt_init, gpt_loss
from ray_tpu.ops.fused_ce import fused_softmax_cross_entropy


def _naive(x, w, t):
    logits = x.astype(jnp.float32) @ w.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, t[:, None], axis=-1)[:, 0]


@pytest.mark.parametrize("vocab,n_chunks", [(4096, None), (4096, 4), (1000, None)])
def test_loss_matches_naive(vocab, n_chunks):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(k1, (64, 32), jnp.float32)
    w = jax.random.normal(k2, (32, vocab), jnp.float32) * 0.1
    t = jax.random.randint(k3, (64,), 0, vocab, jnp.int32)
    got = fused_softmax_cross_entropy(x, w, t, n_chunks)
    want = _naive(x, w, t)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_gradients_match_naive():
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    x = jax.random.normal(k1, (48, 16), jnp.float32)
    w = jax.random.normal(k2, (16, 2048), jnp.float32) * 0.1
    t = jax.random.randint(k3, (48,), 0, 2048, jnp.int32)

    def fused_mean(x, w):
        return fused_softmax_cross_entropy(x, w, t).mean()

    def naive_mean(x, w):
        return _naive(x, w, t).mean()

    gx_f, gw_f = jax.grad(fused_mean, argnums=(0, 1))(x, w)
    gx_n, gw_n = jax.grad(naive_mean, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_n), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_n), rtol=1e-4, atol=1e-6)


def test_weighted_cotangent_flows():
    # non-uniform upstream gradient (e.g. masked/weighted mean) must scale
    # per-token rows of both gradients
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
    x = jax.random.normal(k1, (8, 8), jnp.float32)
    w = jax.random.normal(k2, (8, 1024), jnp.float32) * 0.1
    t = jax.random.randint(k3, (8,), 0, 1024, jnp.int32)
    wts = jnp.arange(1.0, 9.0)

    gx_f = jax.grad(lambda x: (fused_softmax_cross_entropy(x, w, t) * wts).sum())(x)
    gx_n = jax.grad(lambda x: (_naive(x, w, t) * wts).sum())(x)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_n), rtol=1e-4, atol=1e-6)


def test_gpt_loss_fused_matches_naive():
    import dataclasses

    cfg = GPTConfig(vocab_size=2048, seq_len=64, d_model=64, n_layers=2, n_heads=4,
                    dtype="float32")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 65), 0, 2048, jnp.int32)

    fused = gpt_loss(cfg, params, tokens)
    naive = gpt_loss(dataclasses.replace(cfg, fused_loss=False), params, tokens)
    np.testing.assert_allclose(float(fused), float(naive), rtol=1e-5)

    # gradient must flow through scan+remat+custom_vjp composition
    g = jax.grad(lambda p: gpt_loss(cfg, p, tokens))(params)
    gn = jax.grad(lambda p: gpt_loss(dataclasses.replace(cfg, fused_loss=False), p, tokens))(params)
    np.testing.assert_allclose(
        np.asarray(g["lm_head"]["kernel"]),
        np.asarray(gn["lm_head"]["kernel"]),
        rtol=1e-4, atol=1e-7,
    )
    np.testing.assert_allclose(
        np.asarray(g["embed"]["tokens"]),
        np.asarray(gn["embed"]["tokens"]),
        rtol=1e-4, atol=1e-7,
    )


# tier-1 budget (ISSUE 13): ~34s across the matrix on the dev box (9.5 +
# 7.9 + 6.4 + 5.3 + 5.1s for the five heaviest params); grad-level remat
# parity is value-independent of wall clock and the fused-vs-naive loss
# parity tests below keep fused-CE correctness in tier-1
@pytest.mark.slow
@pytest.mark.parametrize(
    "policy,attn_impl,seq",
    [
        ("full", "auto", 32),
        ("dots", "auto", 32),
        ("attn", "auto", 32),
        ("big", "auto", 32),
        # attn_impl="flash" (interpret-mode kernel on CPU) exercises the
        # flash_out/flash_lse checkpoint_name tags that "attn"/"big"
        # actually save — the mechanism behind the TPU remat win; without
        # this, a dropped tag would only show up as a silent perf loss.
        ("attn", "flash", 128),
        ("big", "flash", 128),
    ],
)
def test_remat_policies_agree(policy, attn_impl, seq):
    import dataclasses

    cfg = GPTConfig(vocab_size=512, seq_len=seq, d_model=32, n_layers=2, n_heads=2,
                    dtype="float32", remat_policy=policy, attn_impl=attn_impl)
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, seq + 1), 0, 512, jnp.int32)
    base = dataclasses.replace(cfg, remat=False)
    l1 = float(gpt_loss(cfg, params, tokens))
    l2 = float(gpt_loss(base, params, tokens))
    assert abs(l1 - l2) < 1e-5
    g1 = jax.grad(lambda p: gpt_loss(cfg, p, tokens))(params)
    g2 = jax.grad(lambda p: gpt_loss(base, p, tokens))(params)
    np.testing.assert_allclose(
        np.asarray(g1["blocks"]["attn_qkv"]["kernel"]),
        np.asarray(g2["blocks"]["attn_qkv"]["kernel"]),
        rtol=1e-4, atol=1e-6,
    )
