"""Placement-group tests (reference: gcs_placement_group_manager /
bundle_scheduling_policy.cc behaviors, python/ray/tests/test_placement_group*)."""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.placement_group import (
    placement_group,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


@pytest.fixture
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    try:
        c.shutdown()
    except Exception:
        pass


def test_pg_basic_ready(cluster):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=10)


def test_pg_strict_spread_needs_nodes(cluster):
    cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.wait(timeout_seconds=10)


def test_pg_pending_until_node_added(cluster):
    # Regression: a PG that is infeasible at creation must be placed when
    # capacity arrives later (reference: pending PG retry on node add).
    pg = placement_group([{"CPU": 4}], strategy="PACK")
    assert not pg.wait(timeout_seconds=0.5)
    cluster.add_node(num_cpus=4)
    assert pg.wait(timeout_seconds=10)


def test_pg_replace_after_node_death_no_leak(cluster):
    # Regression: after losing the node hosting one bundle, re-placement must
    # not double-allocate the surviving bundle's resources.
    n2 = cluster.add_node(num_cpus=2)
    n3 = cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_SPREAD")
    assert pg.wait(timeout_seconds=10)
    before = ray_tpu.available_resources().get("CPU", 0.0)
    cluster.remove_node(n3)
    # bundle from the dead node lands on the remaining free node
    assert pg.wait(timeout_seconds=10)
    after = ray_tpu.available_resources().get("CPU", 0.0)
    # dead node removed 2 CPUs of capacity, but its bundle moved onto
    # previously-free CPUs: availability must not go negative/leak
    assert after >= 0.0
    total = ray_tpu.cluster_resources().get("CPU", 0.0)
    assert total == 4.0  # head(2) + n2(2)
    # both bundles still usable: run a task in each
    @ray_tpu.remote(num_cpus=1)
    def ping():
        return "ok"

    refs = [
        ping.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=pg, placement_group_bundle_index=i
            )
        ).remote()
        for i in range(2)
    ]
    assert ray_tpu.get(refs, timeout=30) == ["ok", "ok"]
    remove_placement_group(pg)


def test_pg_task_scheduling(cluster):
    cluster.add_node(num_cpus=2, resources={"TPU": 4})
    pg = placement_group([{"TPU": 2}], strategy="PACK")
    assert pg.wait(timeout_seconds=10)

    @ray_tpu.remote(num_cpus=0, resources={"TPU": 1})
    def use_tpu():
        return "tpu"

    strat = PlacementGroupSchedulingStrategy(placement_group=pg, placement_group_bundle_index=0)
    assert ray_tpu.get(use_tpu.options(scheduling_strategy=strat).remote(), timeout=30) == "tpu"


def test_pg_infeasible_bundle_task_fails_fast(cluster):
    # A task that can never fit its bundle must error, not hang.
    cluster.add_node(num_cpus=2, resources={"TPU": 4})
    pg = placement_group([{"TPU": 2}], strategy="PACK")
    assert pg.wait(timeout_seconds=10)

    @ray_tpu.remote(resources={"TPU": 1})  # implicit num_cpus=1 won't fit
    def needs_cpu():
        return "nope"

    strat = PlacementGroupSchedulingStrategy(placement_group=pg, placement_group_bundle_index=0)
    with pytest.raises(ValueError, match="can never fit"):
        ray_tpu.get(needs_cpu.options(scheduling_strategy=strat).remote(), timeout=30)
