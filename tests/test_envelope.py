"""Scalability envelope (reference: ``release/benchmarks/README.md`` — 1M
queued tasks, 40k actors, 1 GiB broadcast on big clusters). Scaled to this
CI box (1 core) but structurally identical: deep scheduler queues, actor
fan-out, one large object fanned to every node. The full-size numbers are
recorded per round by ``bench_core.py``'s envelope section.
"""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def ray_4cpu():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


# tier1-durations: ~20s on the CI box — the full suite overruns the
# 870s tier-1 budget (truncation, not failures; ROADMAP), so the heaviest
# non-LLM learning/scale tests run as @slow instead of being cut at random
@pytest.mark.slow
def test_deep_task_queue_100k(ray_4cpu):
    """100k no-op tasks queued at once: the signature-bucketed pending queue
    must stay O(signatures) per pass, not O(tasks) (head._PendingQueue) —
    submission and drain both complete in bounded time."""

    @ray_tpu.remote(num_cpus=1)
    def nop(i):
        return i

    t0 = time.monotonic()
    refs = [nop.remote(i) for i in range(100_000)]
    t_submit = time.monotonic() - t0
    out = ray_tpu.get(refs, timeout=600)
    t_total = time.monotonic() - t0
    assert out == list(range(100_000))
    # generous envelope bounds: catching O(n^2) scheduler regressions, not
    # measuring throughput (bench_core does that uncontended)
    assert t_submit < 120, f"submission took {t_submit:.1f}s"
    assert t_total < 540, f"drain took {t_total:.1f}s"


def test_actor_wave_100(ray_4cpu):
    """100 concurrent actors (each a real OS process) all answering."""

    @ray_tpu.remote(num_cpus=0)
    class A:
        def __init__(self, i):
            self.i = i

        def ping(self):
            return self.i

    actors = [A.remote(i) for i in range(100)]
    out = ray_tpu.get([a.ping.remote() for a in actors], timeout=300)
    assert out == list(range(100))
    # second round-trip: all still alive
    out2 = ray_tpu.get([a.ping.remote() for a in actors], timeout=120)
    assert out2 == list(range(100))
    for a in actors:
        ray_tpu.kill(a)


def test_broadcast_256mb_8_nodes():
    """One 256MB object read by a task on each of 8 virtual nodes
    (reference: 1 GiB broadcast to 50 nodes). Same-host shm is zero-copy;
    the data-plane path is exercised separately in test_data_plane."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        for _ in range(7):
            cluster.add_node(num_cpus=1)
        ray_tpu.init(address=cluster.address)

        blob = np.ones((256 << 20) // 8, dtype=np.float64)  # 256MB
        ref = ray_tpu.put(blob)

        @ray_tpu.remote(num_cpus=1)
        def digest(x):
            return float(x[0]) + float(x[-1]) + x.nbytes

        t0 = time.monotonic()
        outs = ray_tpu.get([digest.remote(ref) for _ in range(8)], timeout=300)
        dt = time.monotonic() - t0
        assert outs == [2.0 + (256 << 20)] * 8
        # zero-copy shm reads: 2GB of logical traffic must not take minutes
        assert dt < 120, f"8-node 256MB broadcast took {dt:.1f}s"
    finally:
        cluster.shutdown()
