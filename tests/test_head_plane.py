"""Head-plane upgrades: pubsub, lineage reconstruction, state snapshots.

Reference counterparts: ``src/ray/pubsub/`` (GCS push channels),
``core_worker/object_recovery_manager.h:41`` (lineage reconstruction),
``gcs/gcs_server/gcs_table_storage.cc`` (persistent GCS tables).
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import pubsub


class TestPubsub:
    def test_user_channel_roundtrip(self, ray_start_regular):
        with pubsub.subscribe("my-channel") as sub:
            pubsub.publish("my-channel", {"hello": 1})
            msg = sub.get(timeout=10)
        assert msg == {"hello": 1}

    def test_worker_publishes_driver_receives(self, ray_start_regular):
        @ray_tpu.remote
        def announce(i):
            from ray_tpu.util import pubsub as ps

            ps.publish("events", {"i": i})
            return i

        with pubsub.subscribe("events") as sub:
            ray_tpu.get([announce.remote(i) for i in range(3)])
            got = sorted(sub.get(timeout=10)["i"] for _ in range(3))
        assert got == [0, 1, 2]

    def test_worker_subscribes(self, ray_start_regular):
        @ray_tpu.remote
        class Listener:
            def __init__(self):
                from ray_tpu.util import pubsub as ps

                self.sub = ps.subscribe("to-worker")

            def ready(self):
                return True

            def recv(self):
                return self.sub.get(timeout=10)

        listener = Listener.remote()
        ray_tpu.get(listener.ready.remote(), timeout=30)  # subscription live
        fut = listener.recv.remote()
        time.sleep(0.2)  # let recv start blocking before the publish
        pubsub.publish("to-worker", "ping")
        assert ray_tpu.get(fut, timeout=15) == "ping"

    def test_builtin_actor_channel(self, ray_start_regular):
        with pubsub.subscribe("actors") as sub:

            @ray_tpu.remote
            class A:
                def ping(self):
                    return 1

            a = A.options(name="pub-actor").remote()
            ray_tpu.get(a.ping.remote())
            events = []
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                events += sub.poll()
                if any(e["event"] == "ALIVE" and e["name"] == "pub-actor" for e in events):
                    break
                time.sleep(0.05)
        assert any(e["event"] == "ALIVE" and e["name"] == "pub-actor" for e in events)

    def test_builtin_nodes_channel(self, ray_start_cluster):
        cluster = ray_start_cluster()
        ray_tpu.init(address=cluster.address)
        try:
            with pubsub.subscribe("nodes") as sub:
                node = cluster.add_node(num_cpus=1)
                deadline = time.monotonic() + 10
                added = []
                while time.monotonic() < deadline:
                    added += [e for e in sub.poll() if e["event"] == "added"]
                    if added:
                        break
                    time.sleep(0.05)
            assert added
        finally:
            ray_tpu.shutdown()


class TestLineageReconstruction:
    def test_lost_shm_object_is_recomputed(self, ray_start_regular):
        """Kill an object's shm backing behind the head's back; the next get
        reports it lost and the creating task re-runs transparently."""
        calls_path = "/tmp/lineage_calls_%d" % os.getpid()
        if os.path.exists(calls_path):
            os.unlink(calls_path)

        @ray_tpu.remote
        def produce():
            with open(calls_path, "a") as f:
                f.write("x")
            return np.arange(300_000)  # 2.4MB -> dedicated segment

        ref = produce.remote()
        first = ray_tpu.get(ref, timeout=60)
        assert first[-1] == 299_999

        # destroy the backing segment out-of-band (simulated node loss)
        from ray_tpu._private.runtime import get_ctx

        head = get_ctx().head
        with head.lock:
            ent = head.objects[ref._id]
            assert ent.shm is not None and ent.lineage is not None
            head.shm_owner.unlink(ent.shm)
            # drop our cached reader so the re-read hits shm again
        with get_ctx()._readers_lock:
            get_ctx()._readers.pop(ref._id, None)

        again = ray_tpu.get(ref, timeout=60)
        assert np.array_equal(again, first)
        with open(calls_path) as f:
            assert f.read() == "xx", "creating task should have re-run exactly once"
        os.unlink(calls_path)

    def test_put_objects_are_not_reconstructable(self, ray_start_regular):
        """ray.put objects have no lineage: losing one is a real loss."""
        ref = ray_tpu.put(np.arange(300_000))
        from ray_tpu._private.runtime import get_ctx

        head = get_ctx().head
        with head.lock:
            ent = head.objects[ref._id]
            assert ent.lineage is None
            head.shm_owner.unlink(ent.shm)
        with get_ctx()._readers_lock:
            get_ctx()._readers.pop(ref._id, None)
        with pytest.raises(ray_tpu.exceptions.ObjectLostError):
            ray_tpu.get(ref, timeout=30)

    def test_corrupt_spill_file_triggers_reconstruction(self, ray_start_regular):
        @ray_tpu.remote
        def produce():
            return np.ones(400_000)

        ref = produce.remote()
        ray_tpu.get(ref, timeout=60)
        from ray_tpu._private.runtime import get_ctx

        head = get_ctx().head
        with head.lock:
            ent = head.objects[ref._id]
            # force-spill, then corrupt the file
            head._spill_one(ref._id, ent)
            assert ent.spill_path
            with open(ent.spill_path, "wb") as f:
                f.write(b"garbage")
        with get_ctx()._readers_lock:
            get_ctx()._readers.pop(ref._id, None)
        v = ray_tpu.get(ref, timeout=60)
        assert v.sum() == 400_000


class TestSnapshot:
    def test_kv_and_functions_survive_head_restart(self, tmp_path):
        from ray_tpu._private.config import GLOBAL_CONFIG

        snap = str(tmp_path / "gcs.snap")
        old = GLOBAL_CONFIG.gcs_snapshot_path
        try:
            from ray_tpu._private.runtime import get_ctx

            ray_tpu.init(num_cpus=2, _system_config={"gcs_snapshot_path": snap})
            try:
                get_ctx().call("kv_put", key="persist-key", value=b"persist-value")
            finally:
                ray_tpu.shutdown()
            assert os.path.exists(snap)

            ray_tpu.init(num_cpus=2, _system_config={"gcs_snapshot_path": snap})
            try:
                assert get_ctx().call("kv_get", key="persist-key") == b"persist-value"
            finally:
                ray_tpu.shutdown()
        finally:
            GLOBAL_CONFIG.gcs_snapshot_path = old
            if ray_tpu.is_initialized():
                ray_tpu.shutdown()

    def test_no_snapshot_without_path(self, ray_start_regular):
        from ray_tpu._private.runtime import get_ctx

        assert get_ctx().head._snapshot_path is None
