"""Train integrations: HF transformers weight import, orbax checkpoints.

Reference counterparts: ``python/ray/train/huggingface/transformers/``
(framework interop) and ``train/_checkpoint.py`` storage. Everything here is
offline: the HF model is randomly initialized from a local config — no hub
downloads.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


def _tiny_hf_model():
    transformers = pytest.importorskip("transformers")
    cfg = transformers.GPT2Config(
        vocab_size=96,
        n_positions=32,
        n_embd=64,
        n_layer=2,
        n_head=2,
        resid_pdrop=0.0,
        embd_pdrop=0.0,
        attn_pdrop=0.0,
    )
    model = transformers.GPT2LMHeadModel(cfg)
    model.eval()
    return model


class TestHuggingFace:
    # tier1-durations: ~48s on the CI box — the full suite overruns the
    # 870s tier-1 budget (truncation, not failures; ROADMAP), so the heaviest
    # non-LLM learning/scale tests run as @slow instead of being cut at random
    @pytest.mark.slow
    def test_gpt2_logits_match(self):
        """Converted weights reproduce the torch forward pass.

        This is the strongest possible conversion check: same tokens through
        HF torch GPT-2 and through ray_tpu's scan/pjit GPT must give the
        same logits.
        """
        import torch

        from ray_tpu.models.gpt import gpt_forward
        from ray_tpu.train.integrations import load_hf_gpt2

        model = _tiny_hf_model()
        cfg, params = load_hf_gpt2(model)
        cfg = __import__("dataclasses").replace(cfg, dtype="float32", remat=False)

        tokens = np.random.RandomState(0).randint(0, 96, size=(2, 16)).astype(np.int32)
        with torch.no_grad():
            ref = model(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()
        got = np.asarray(gpt_forward(cfg, params, jnp.asarray(tokens)))
        np.testing.assert_allclose(got, ref, atol=2e-3, rtol=2e-3)

    # tier-1 budget (ISSUE 13): 15.0s measured on the dev box (HF model
    # load + two forward paths); the logits-parity @slow test already
    # covers the HF bridge in the slow tier
    @pytest.mark.slow
    def test_vocab_padding(self):
        from ray_tpu.train.integrations import load_hf_gpt2

        model = _tiny_hf_model()
        cfg, params = load_hf_gpt2(model, pad_vocab_to_multiple=128)
        assert cfg.vocab_size == 128
        assert params["embed"]["tokens"].shape == (128, 64)
        assert params["lm_head"]["kernel"].shape == (64, 128)
        # padded rows are zero
        assert float(jnp.abs(params["embed"]["tokens"][96:]).max()) == 0.0

    def test_config_mapping(self):
        transformers = pytest.importorskip("transformers")

        from ray_tpu.train.integrations import gpt_config_from_hf

        hf = transformers.GPT2Config(
            vocab_size=500, n_positions=128, n_embd=96, n_layer=3, n_head=4
        )
        cfg = gpt_config_from_hf(hf, dtype="float32")
        assert (cfg.vocab_size, cfg.seq_len, cfg.d_model, cfg.n_layers, cfg.n_heads) == (
            500, 128, 96, 3, 4,
        )
        assert cfg.dtype == "float32"


class TestOrbax:
    def test_roundtrip(self, tmp_path):
        from ray_tpu.train.integrations import (
            load_pytree_checkpoint,
            save_pytree_checkpoint,
        )

        state = {
            "params": {"w": jnp.arange(12.0).reshape(3, 4), "b": jnp.zeros(4)},
            "step": jnp.int32(7),
        }
        ckpt = save_pytree_checkpoint(state, str(tmp_path / "ck"))
        restored = load_pytree_checkpoint(ckpt)
        np.testing.assert_array_equal(restored["params"]["w"], state["params"]["w"])
        assert int(restored["step"]) == 7

    def test_restore_with_target_structure(self, tmp_path):
        from ray_tpu.train.integrations import (
            load_pytree_checkpoint,
            save_pytree_checkpoint,
        )

        state = {"w": jnp.ones((4, 4)), "n": jnp.int32(3)}
        save_pytree_checkpoint(state, str(tmp_path / "ck"))
        target = {"w": jnp.zeros((4, 4)), "n": jnp.int32(0)}
        restored = load_pytree_checkpoint(str(tmp_path / "ck"), target=target)
        np.testing.assert_array_equal(restored["w"], np.ones((4, 4)))

    def test_session_report_carries_orbax_checkpoint(self, ray_start_regular, tmp_path):
        """End-to-end: a JaxTrainer worker saves an orbax checkpoint through
        session.report and the Result hands it back."""
        import ray_tpu.train as train
        from ray_tpu.train import ScalingConfig
        from ray_tpu.train.integrations import (
            load_pytree_checkpoint,
            save_pytree_checkpoint,
        )

        def loop(config):
            import os

            import ray_tpu.train as train

            state = {"w": jnp.full((2, 2), 5.0)}
            rank = train.get_context().get_world_rank()
            path = os.path.join(config["dir"], f"rank{rank}")
            ckpt = save_pytree_checkpoint(state, path)
            train.report({"loss": 1.0}, checkpoint=ckpt)

        trainer = train.JaxTrainer(
            loop,
            train_loop_config={"dir": str(tmp_path)},
            scaling_config=ScalingConfig(num_workers=1),
        )
        result = trainer.fit()
        assert result.checkpoint is not None
        restored = load_pytree_checkpoint(result.checkpoint)
        np.testing.assert_array_equal(restored["w"], np.full((2, 2), 5.0))


# ---------------------------------------------------------------------------
# GPT-J (round 5: the north-star architecture for real — VERDICT r4 #4)
# ---------------------------------------------------------------------------


def _tiny_hf_gptj():
    import torch
    from transformers import GPTJConfig as HFGPTJConfig
    from transformers import GPTJForCausalLM

    torch.manual_seed(0)
    hf_cfg = HFGPTJConfig(
        vocab_size=96,
        n_positions=32,
        n_embd=64,
        n_layer=3,
        n_head=4,
        rotary_dim=8,
        attn_pdrop=0.0,
        embd_pdrop=0.0,
        resid_pdrop=0.0,
    )
    model = GPTJForCausalLM(hf_cfg)
    model.eval()
    return model


class TestGPTJ:
    def test_gptj_logits_match(self):
        """Logit-exact import: same tokens through HF torch GPT-J and
        through the scan/rotary/parallel-block JAX GPT-J must agree —
        exercises rotary (interleaved), parallel residual, untied biased
        head, no-bias projections."""
        import torch

        from ray_tpu.models.gptj import gptj_forward
        from ray_tpu.train.integrations import load_hf_gptj

        model = _tiny_hf_gptj()
        cfg, params = load_hf_gptj(model)
        cfg = __import__("dataclasses").replace(
            cfg, dtype="float32", remat=False, attn_impl="xla", fused_loss=False
        )

        tokens = np.random.RandomState(0).randint(0, 96, size=(2, 16)).astype(np.int32)
        with torch.no_grad():
            ref = model(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()
        got = np.asarray(gptj_forward(cfg, params, jnp.asarray(tokens)))
        np.testing.assert_allclose(got, ref, atol=2e-3, rtol=2e-3)

    def test_gptj_vocab_padding_blocks_padded_ids(self):
        from ray_tpu.models.gptj import gptj_forward
        from ray_tpu.train.integrations import load_hf_gptj

        model = _tiny_hf_gptj()
        cfg, params = load_hf_gptj(model, pad_vocab_to_multiple=128)
        assert cfg.vocab_size == 128
        cfg = __import__("dataclasses").replace(
            cfg, dtype="float32", remat=False, attn_impl="xla"
        )
        tokens = np.random.RandomState(1).randint(0, 96, size=(1, 8)).astype(np.int32)
        logits = np.asarray(gptj_forward(cfg, params, jnp.asarray(tokens)))
        # -1e9 head bias on padded ids: argmax can never land there
        assert logits[..., 96:].max() < -1e8

    def test_gptj_decode_matches_hf_greedy(self):
        """KV-cache greedy decode emits the same continuation as HF
        ``generate(do_sample=False)`` — validates the cache/rotary-offset
        path, not just the parallel forward."""
        import torch

        from ray_tpu.models.gptj import gptj_decode
        from ray_tpu.train.integrations import load_hf_gptj

        model = _tiny_hf_gptj()
        cfg, params = load_hf_gptj(model)
        cfg = __import__("dataclasses").replace(
            cfg, dtype="float32", remat=False, attn_impl="xla"
        )
        prompt = np.random.RandomState(2).randint(0, 96, size=(1, 7)).astype(np.int32)
        with torch.no_grad():
            ref = model.generate(
                torch.from_numpy(prompt.astype(np.int64)),
                max_new_tokens=6,
                do_sample=False,
                pad_token_id=0,
            ).numpy()
        got = np.asarray(gptj_decode(cfg, params, jnp.asarray(prompt), 6))
        np.testing.assert_array_equal(got, ref)

    def test_gptj_fused_loss_matches_naive(self):
        from ray_tpu.models.gptj import gptj_loss
        from ray_tpu.train.integrations import load_hf_gptj

        model = _tiny_hf_gptj()
        cfg, params = load_hf_gptj(model)
        import dataclasses

        tokens = jnp.asarray(
            np.random.RandomState(3).randint(0, 96, size=(2, 17)).astype(np.int32)
        )
        cfg32 = dataclasses.replace(
            cfg, dtype="float32", remat=False, attn_impl="xla"
        )
        fused = gptj_loss(dataclasses.replace(cfg32, fused_loss=True), params, tokens)
        naive = gptj_loss(dataclasses.replace(cfg32, fused_loss=False), params, tokens)
        np.testing.assert_allclose(float(fused), float(naive), atol=1e-4, rtol=1e-5)

    # tier-1 budget (ISSUE 13): 10.9s measured on the dev box; fused-CE
    # VJP parity is also pinned across configs by tests/test_fused_ce.py
    @pytest.mark.slow
    def test_gptj_fused_loss_grads(self):
        """Bias-aware fused CE VJP: grads match the naive loss (incl. the
        lm_head bias grad, which only GPT-J exercises)."""
        import jax

        from ray_tpu.models.gptj import gptj_loss
        from ray_tpu.train.integrations import load_hf_gptj

        model = _tiny_hf_gptj()
        cfg, params = load_hf_gptj(model)
        import dataclasses

        cfg32 = dataclasses.replace(cfg, dtype="float32", remat=False, attn_impl="xla")
        tokens = jnp.asarray(
            np.random.RandomState(4).randint(0, 96, size=(1, 9)).astype(np.int32)
        )
        g_fused = jax.grad(
            lambda p: gptj_loss(dataclasses.replace(cfg32, fused_loss=True), p, tokens)
        )(params)
        g_naive = jax.grad(
            lambda p: gptj_loss(dataclasses.replace(cfg32, fused_loss=False), p, tokens)
        )(params)
        for a, b in zip(
            jax.tree_util.tree_leaves(g_fused), jax.tree_util.tree_leaves(g_naive)
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-3)


class TestFlaxBridge:
    """flax/linen bridge (round 5): any linen module trains on the sharded
    stack (the JAX-ecosystem analog of the reference's Lightning/DeepSpeed
    trainer integrations)."""

    def _setup(self, overrides=None):
        import flax.linen as nn
        import optax

        from ray_tpu.parallel.mesh import MeshConfig, make_mesh
        from ray_tpu.train.integrations.flax_bridge import build_flax_train_step

        class MLP(nn.Module):
            @nn.compact
            def __call__(self, batch):
                x = batch["x"]
                x = nn.Dense(256)(x)
                x = nn.relu(x)
                return nn.Dense(8)(x)

        mesh = make_mesh(MeshConfig(dp=2, fsdp=4, tp=1, sp=1))

        def loss_fn(apply_fn, params, batch):
            logits = apply_fn({"params": params}, batch)
            onehot = jax.nn.one_hot(batch["y"], 8)
            return -jnp.mean(
                jnp.sum(jax.nn.log_softmax(logits) * onehot, axis=-1)
            )

        rs = np.random.RandomState(0)
        batch = {
            "x": rs.randn(16, 32).astype(np.float32),
            "y": rs.randint(0, 8, 16).astype(np.int32),
        }
        init_fn, step_fn = build_flax_train_step(
            MLP(), loss_fn, optax.adam(1e-2), mesh, batch,
            min_shard_size=1024, sharding_overrides=overrides,
        )
        return init_fn, step_fn, batch, mesh

    def test_flax_module_trains_sharded(self):
        init_fn, step_fn, batch, mesh = self._setup()
        state = init_fn()
        # the big Dense kernels actually scattered over fsdp
        kernel = state.params["Dense_0"]["kernel"]
        spec = kernel.sharding.spec
        assert "fsdp" in str(spec), spec
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        losses = []
        for _ in range(12):
            state, loss = step_fn(state, jb)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, losses

    def test_sharding_overrides(self):
        from jax.sharding import PartitionSpec as P

        init_fn, _step, _batch, _mesh = self._setup(
            overrides=[(r"Dense_1/kernel", P(None, None))]
        )
        state = init_fn()
        assert state.params["Dense_1"]["kernel"].sharding.spec == P(None, None)
