"""State API + metrics + timeline tests.

Reference coverage themes: ``python/ray/tests/test_state_api*.py``,
``test_metrics_agent.py``, ``ray timeline``.
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu.util import state
from ray_tpu.util.metrics import Counter, Gauge, Histogram, collect, prometheus_text


def test_list_and_summarize_tasks(ray_start_regular):
    @ray_tpu.remote
    def work(x):
        return x + 1

    ray_tpu.get([work.remote(i) for i in range(5)])

    events = state.get_task_events()
    finished = [e for e in events if e["state"] == "FINISHED"]
    assert len(finished) >= 5
    # every finished task has a matching RUNNING event with an earlier time
    runs = {e["task_id"]: e["time"] for e in events if e["state"] == "RUNNING"}
    for ev in finished:
        assert ev["task_id"] in runs
        assert ev["time"] >= runs[ev["task_id"]]

    summ = state.summarize_tasks()
    assert summ["by_state"].get("FINISHED", 0) >= 5
    assert any("work" in fn for fn in summ["by_func"])


def test_list_actors_and_nodes(ray_start_regular):
    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.options(name="obs_actor").remote()
    ray_tpu.get(a.ping.remote())

    actors = state.list_actors()
    mine = [x for x in actors if x["name"] == "obs_actor"]
    assert mine and mine[0]["state"] == "ALIVE" and mine[0]["class_name"] == "A"

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["Alive"]

    summ = state.summary()
    assert summ["actors"]["by_state"].get("ALIVE", 0) >= 1


def test_failed_task_event(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("x")

    with pytest.raises(Exception):
        ray_tpu.get(boom.remote())
    events = state.get_task_events()
    assert any(e["state"] == "FAILED" for e in events)


def test_timeline_chrome_trace(ray_start_regular, tmp_path):
    @ray_tpu.remote
    def slow():
        time.sleep(0.05)
        return 1

    ray_tpu.get([slow.remote() for _ in range(3)])
    path = tmp_path / "trace.json"
    trace = state.timeline(str(path))
    assert len(trace) >= 3
    ev = trace[0]
    assert ev["ph"] == "X" and ev["dur"] > 0
    loaded = json.loads(path.read_text())
    assert len(loaded) == len(trace)
    slow_evs = [e for e in loaded if "slow" in (e["name"] or "")]
    assert slow_evs and all(e["dur"] >= 40_000 for e in slow_evs)  # >=40ms in us


def test_placement_group_listing(ray_start_regular):
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    ray_tpu.get(pg.ready())
    pgs = state.list_placement_groups()
    assert len(pgs) == 1
    assert pgs[0]["state"] == "CREATED"
    assert len(pgs[0]["bundles"]) == 2


def test_metrics_counter_gauge_histogram(ray_start_regular):
    c = Counter("obs_requests", "requests served", tag_keys=("route",))
    c.inc(1, tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    c.inc(5, tags={"route": "/b"})
    g = Gauge("obs_queue_depth", "queue depth")
    g.set(3)
    g.set(7)
    h = Histogram("obs_latency", "latency s", boundaries=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    data = collect()
    metrics = data["metrics"]
    route_a = json.dumps({"route": "/a"}, separators=(",", ":"))
    route_b = json.dumps({"route": "/b"}, separators=(",", ":"))
    assert metrics["obs_requests"][route_a] == 3
    assert metrics["obs_requests"][route_b] == 5
    assert metrics["obs_queue_depth"][""] == 7
    hist = metrics["obs_latency"][""]
    assert hist[:3] == [1, 1, 1]     # one obs per bucket (incl overflow)
    assert hist[-1] == 3             # count
    assert abs(hist[-2] - 5.55) < 1e-6  # sum

    text = prometheus_text()
    assert "ray_tpu_obs_requests" in text
    assert 'route="/a"' in text


def test_metrics_from_workers_merge(ray_start_regular):
    @ray_tpu.remote
    def record(i):
        from ray_tpu.util.metrics import Counter, flush

        c = Counter("obs_worker_hits", "per-worker counter")
        c.inc(1)
        flush()
        return i

    ray_tpu.get([record.remote(i) for i in range(4)])
    data = collect()
    total = sum(data["metrics"].get("obs_worker_hits", {}).values())
    assert total == 4


def test_metric_tag_validation(ray_start_regular):
    c = Counter("obs_tagged", tag_keys=("a",))
    with pytest.raises(ValueError):
        c.inc(1, tags={"bogus": "x"})
    with pytest.raises(ValueError):
        Counter("bad name")


# -- per-node reporter + stuck-worker stack dumps (reporter.py) --------------


def test_worker_stack_dumps_show_running_function(ray_start_regular):
    """SIGUSR1 stack dumps reach INSIDE a busy worker: the dump must show
    the user function currently executing (the py-spy property — works
    without worker cooperation). Reference: dashboard profile_manager."""
    import time

    import ray_tpu
    from ray_tpu.util import state

    @ray_tpu.remote
    def spin_here_marker_fn():
        t0 = time.time()
        while time.time() - t0 < 15:
            time.sleep(0.01)
        return True

    ref = spin_here_marker_fn.remote()
    time.sleep(1.0)  # let it start spinning
    stacks = state.get_worker_stacks()
    text = "\n".join(t for per in stacks.values() for t in per.values())
    assert "spin_here_marker_fn" in text, text[-2000:]
    ray_tpu.cancel(ref, force=True)


def test_node_stats_reported(ray_start_regular):
    import time

    from ray_tpu.util import state

    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        stats = state.get_node_stats()
        if stats and any("mem_percent" in s for s in stats.values()):
            break
        time.sleep(0.5)
    assert stats
    s = next(iter(stats.values()))
    assert 0 < s["mem_percent"] <= 100
    assert s["disk_total_bytes"] > 0


def test_agent_node_stats_and_stacks(ray_start_regular):
    """Agent-hosted workers are covered too: stats pushed by the agent,
    dumps collected through it."""
    import time

    import ray_tpu
    from ray_tpu._private.node_agent import NodeAgent
    from ray_tpu._private.runtime import get_ctx
    from ray_tpu.util import state

    head = get_ctx().head
    host, port = head.listen_tcp("127.0.0.1", 0)
    agent = NodeAgent(f"{host}:{port}", head.authkey, resources={"CPU": 2.0, "agentland": 5.0}).start()
    try:
        @ray_tpu.remote(resources={"agentland": 1.0})
        def agent_spin_marker():
            t0 = time.time()
            while time.time() - t0 < 15:
                time.sleep(0.01)
            return True

        ref = agent_spin_marker.remote()
        time.sleep(2.0)
        stacks = state.get_worker_stacks()
        agent_hex = agent.node_id_bin.hex()
        assert agent_hex in stacks, list(stacks)
        text = "\n".join(stacks[agent_hex].values())
        assert "agent_spin_marker" in text, text[-1500:]
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            stats = state.get_node_stats()
            if stats.get(agent_hex):
                break
            time.sleep(0.5)
        assert stats.get(agent_hex), "agent never pushed stats"
        ray_tpu.cancel(ref, force=True)
    finally:
        agent.shutdown()


def test_worker_cpu_profile_shows_hot_function(ray_start_regular):
    """On-demand sampling profiler (reference: dashboard py-spy
    cpu_profile): collapsed stacks of a busy worker must attribute samples
    to the user function that is burning the CPU, leaf-most frame last."""
    import time

    import ray_tpu
    from ray_tpu.util import state

    @ray_tpu.remote
    def burn_cpu_marker_fn():
        t0 = time.time()
        while time.time() - t0 < 15:
            sum(range(256))
        return True

    ref = burn_cpu_marker_fn.remote()
    try:
        time.sleep(1.0)  # ensure the worker is inside the burn loop
        prof = state.profile_workers(duration_s=0.6, interval_ms=5.0)
        blobs = [t for per in prof.values() for t in per.values()
                 if isinstance(t, str)]
        assert blobs, prof
        text = "\n".join(blobs)
        assert "burn_cpu_marker_fn" in text, text[:2000]
        # collapsed format: every line is "frame;frame;... count"
        hot = [l for l in text.splitlines() if "burn_cpu_marker_fn" in l][0]
        stack, count = hot.rsplit(" ", 1)
        assert int(count) >= 1 and ";" in stack
    finally:
        ray_tpu.cancel(ref, force=True)
