"""Job submission tests (reference: ``dashboard/modules/job/tests`` themes:
submit/status/logs/stop/list, entrypoint attaching back to the cluster)."""

import os
import sys
import tempfile
import time

import pytest

import ray_tpu
from ray_tpu import job


def test_submit_success_and_logs(ray_start_regular):
    jid = job.submit_job(f"{sys.executable} -c \"print('hello from job')\"")
    assert job.wait_job(jid, timeout=120) == job.SUCCEEDED
    assert "hello from job" in job.get_job_logs(jid)
    jobs = job.list_jobs()
    assert any(j["job_id"] == jid and j["status"] == job.SUCCEEDED for j in jobs)


def test_failed_job(ray_start_regular):
    jid = job.submit_job(f"{sys.executable} -c \"import sys; print('boom'); sys.exit(3)\"")
    assert job.wait_job(jid, timeout=120) == job.FAILED
    logs = job.get_job_logs(jid)
    assert "boom" in logs and "exit code 3" in logs


def test_stop_running_job(ray_start_regular):
    jid = job.submit_job(f"{sys.executable} -c \"import time; time.sleep(60)\"")
    deadline = time.time() + 30
    while job.get_job_status(jid) == job.PENDING and time.time() < deadline:
        time.sleep(0.1)
    assert job.stop_job(jid)
    assert job.wait_job(jid, timeout=60) == job.STOPPED


def test_env_vars_and_working_dir(ray_start_regular, tmp_path):
    jid = job.submit_job(
        f"{sys.executable} -c \"import os; print('V=' + os.environ['MY_JOB_VAR'], 'D=' + os.getcwd())\"",
        env_vars={"MY_JOB_VAR": "42"},
        working_dir=str(tmp_path),
    )
    assert job.wait_job(jid, timeout=120) == job.SUCCEEDED
    logs = job.get_job_logs(jid)
    assert "V=42" in logs
    assert f"D={tmp_path}" in logs


def test_entrypoint_attaches_to_cluster(ray_start_regular):
    """With a TCP listener up, the job's subprocess gets RAY_TPU_ADDRESS and
    can drive the SAME cluster that runs it."""
    from ray_tpu._private.runtime import get_ctx

    get_ctx().head.listen_tcp("127.0.0.1", 0)
    script = (
        "import os, ray_tpu\n"
        "ray_tpu.init(address=os.environ['RAY_TPU_ADDRESS'])\n"
        "@ray_tpu.remote\n"
        "def f(x):\n"
        "    return x + 1\n"
        "print('RESULT', ray_tpu.get(f.remote(41), timeout=60))\n"
        "ray_tpu.shutdown()\n"
    )
    path = tempfile.mktemp(suffix=".py")
    with open(path, "w") as f:
        f.write(script)
    env_path = "/root/repo" + os.pathsep + os.environ.get("PYTHONPATH", "")
    jid = job.submit_job(
        f"{sys.executable} {path}", env_vars={"PYTHONPATH": env_path}
    )
    assert job.wait_job(jid, timeout=180) == job.SUCCEEDED, job.get_job_logs(jid)
    assert "RESULT 42" in job.get_job_logs(jid)
