"""Conda + container runtime_env tiers (reference:
``python/ray/_private/runtime_env/conda.py``, ``container.py``;
``python/ray/tests/test_runtime_env_conda_and_pip.py`` /
``test_container.py`` themes). Both tiers are driven through FAKE
binaries that record their command lines — the real ones need a conda
installation / a container runtime, neither of which CI has."""

import json
import os
import shutil
import stat
import sys
import uuid

import pytest

import ray_tpu


def _write_exe(path, body):
    with open(path, "w") as f:
        f.write(f"#!{sys.executable}\n" + body)
    os.chmod(path, os.stat(path).st_mode | stat.S_IEXEC)
    return str(path)


@pytest.fixture
def fake_conda(tmp_path, monkeypatch):
    """A conda stand-in: `env create -p P -f YML` materializes a prefix with
    a site-packages marker module + a bin tool; `env list --json` reports
    the envs it created (plus a pretend named env). Every invocation is
    appended to a log."""
    log = tmp_path / "conda_calls.log"
    named_prefix = tmp_path / "envs" / "preexisting"
    body = f"""
import json, os, sys
LOG = {str(log)!r}
NAMED = {str(named_prefix)!r}
with open(LOG, "a") as f:
    f.write(json.dumps(sys.argv[1:]) + "\\n")
args = sys.argv[1:]
if args[:2] == ["env", "create"]:
    prefix = args[args.index("-p") + 1]
    site = os.path.join(prefix, "lib",
                        f"python{{sys.version_info[0]}}.{{sys.version_info[1]}}",
                        "site-packages")
    os.makedirs(site, exist_ok=True)
    os.makedirs(os.path.join(prefix, "bin"), exist_ok=True)
    with open(os.path.join(site, "conda_marker_mod.py"), "w") as f:
        f.write("VALUE = 'from-conda-env'\\n")
    tool = os.path.join(prefix, "bin", "condatool")
    with open(tool, "w") as f:
        f.write("#!/bin/sh\\necho tool\\n")
    os.chmod(tool, 0o755)
elif args[:2] == ["env", "list"]:
    os.makedirs(os.path.join(NAMED, "bin"), exist_ok=True)
    print(json.dumps({{"envs": [NAMED]}}))
"""
    exe = _write_exe(tmp_path / "conda", body)
    monkeypatch.setenv("RAY_TPU_CONDA_EXE", exe)
    return {"log": log, "named_prefix": named_prefix}


def _conda_create_calls(log):
    if not log.exists():
        return []
    return [
        json.loads(line)
        for line in log.read_text().splitlines()
        if json.loads(line)[:2] == ["env", "create"]
    ]


def test_conda_yaml_env_builds_activates_and_caches(ray_start_regular, fake_conda):
    yml = {
        "name": "t",
        "dependencies": ["pip", str(uuid.uuid4())],  # uuid => unique hash per run
    }

    @ray_tpu.remote
    def probe():
        import conda_marker_mod

        return (
            conda_marker_mod.VALUE,
            os.environ.get("CONDA_PREFIX", ""),
            shutil.which("condatool") is not None,
        )

    env = {"conda": yml}
    val, prefix, tool = ray_tpu.get(
        probe.options(runtime_env=env).remote(), timeout=90
    )
    assert val == "from-conda-env"
    assert prefix.startswith(os.path.join(__import__("tempfile").gettempdir(), "ray_tpu_runtime_env"))
    assert tool

    # same yml again: the cached prefix is reused, no second create
    ray_tpu.get(probe.options(runtime_env=env).remote(), timeout=90)
    assert len(_conda_create_calls(fake_conda["log"])) == 1

    # the env never leaks into plain tasks on the (reused) pooled worker
    @ray_tpu.remote
    def plain():
        return os.environ.get("CONDA_PREFIX")

    assert ray_tpu.get(plain.remote(), timeout=60) in (None, "")


def test_conda_named_env_resolves_node_side(ray_start_regular, fake_conda):
    @ray_tpu.remote
    def probe():
        return os.environ.get("CONDA_PREFIX", "")

    got = ray_tpu.get(
        probe.options(runtime_env={"conda": "preexisting"}).remote(), timeout=90
    )
    assert got == str(fake_conda["named_prefix"])
    assert len(_conda_create_calls(fake_conda["log"])) == 0  # resolve, not create

    with pytest.raises(Exception):
        ray_tpu.get(
            probe.options(runtime_env={"conda": "no-such-env"}).remote(), timeout=90
        )


def test_conda_real_binary_smoke(ray_start_regular):
    """Offline-tolerant: only runs where a real conda exists (resolving the
    always-present base env needs no network)."""
    if os.environ.get("RAY_TPU_CONDA_EXE") or not shutil.which("conda"):
        pytest.skip("no real conda on this machine")

    @ray_tpu.remote
    def probe():
        return os.environ.get("CONDA_PREFIX", "")

    got = ray_tpu.get(probe.options(runtime_env={"conda": "base"}).remote(), timeout=120)
    assert got


@pytest.fixture
def fake_runner(tmp_path):
    """A podman stand-in: records its argv, then execs the wrapped worker
    command with the --env vars applied — so the containerized actor REALLY
    runs and the full create->call->result path is exercised."""
    log = tmp_path / "runner_calls.json"
    body = f"""
import json, os, sys
LOG = {str(log)!r}
args = sys.argv[1:]
with open(LOG, "w") as f:
    json.dump(args, f)
env = dict(os.environ)
i = 0
while i < len(args):
    if args[i] == "--env":
        k, _, v = args[i + 1].partition("=")
        env[k] = v
        i += 2
    else:
        i += 1
k = args.index("ray_tpu._private.worker_main")
os.execve(sys.executable, [sys.executable, "-m"] + args[k:], env)
"""
    exe = _write_exe(tmp_path / "podman", body)
    return {"exe": exe, "log": log}


def test_container_actor_spawns_through_runner(ray_start_regular, fake_runner):
    @ray_tpu.remote(
        runtime_env={
            "container": {
                "image": "example.io/worker:v1",
                "run_options": ["--device=/dev/fuse"],
                "runner": fake_runner["exe"],
            }
        }
    )
    class Boxed:
        def whoami(self):
            return os.getpid()

    a = Boxed.remote()
    pid = ray_tpu.get(a.whoami.remote(), timeout=90)
    assert pid != os.getpid()

    argv = json.loads(fake_runner["log"].read_text())
    assert argv[0] == "run" and "--rm" in argv
    # host namespaces + the three binds the worker needs to function
    assert "--network=host" in argv and "--ipc=host" in argv and "--pid=host" in argv
    binds = [argv[i + 1] for i, a_ in enumerate(argv) if a_ == "-v"]
    assert any(b.startswith("/tmp:") for b in binds)
    assert any(b.startswith("/dev/shm:") for b in binds)
    # user run_options ride along; image is the last pre-command token
    assert "--device=/dev/fuse" in argv
    img_i = argv.index("example.io/worker:v1")
    assert argv[img_i + 1] == "python3"  # default worker_python
    # PYTHONPATH crosses the boundary as an explicit --env
    envs = [argv[i + 1] for i, a_ in enumerate(argv) if a_ == "--env"]
    assert any(e.startswith("PYTHONPATH=") for e in envs)


def test_container_rejected_for_pooled_tasks(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(ValueError, match="dedicated worker"):
        f.options(runtime_env={"container": {"image": "x"}}).remote()


def test_container_validation(ray_start_regular):
    @ray_tpu.remote(runtime_env={"container": {"image": "x", "bogus": 1}})
    class A:
        pass

    with pytest.raises(ValueError, match="bogus"):
        A.remote()

    @ray_tpu.remote(runtime_env={"container": "just-a-string"})
    class B:
        pass

    with pytest.raises(TypeError):
        B.remote()
