"""Object lifetime / refcount regressions (reference: reference_count.h
semantics — a live ObjectRef keeps its object alive across arbitrary reuse)."""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import RayActorError


def test_put_ref_survives_task_use(ray_start_regular):
    # Regression: put objects must not be evicted after first use as an arg.
    big = ray_tpu.put(list(range(50_000)))  # large enough for the shm path

    @ray_tpu.remote
    def length(x):
        return len(x)

    assert ray_tpu.get(length.remote(big)) == 50_000
    # second use + direct get must still work while the ref is alive
    assert ray_tpu.get(length.remote(big)) == 50_000
    assert len(ray_tpu.get(big, timeout=10)) == 50_000


def test_actor_arg_pinned_until_execution(ray_start_regular):
    # Regression: actor-method args must be pinned even if the driver drops
    # its ref right after submission.
    @ray_tpu.remote
    class Consumer:
        def consume(self, x):
            return len(x)

    c = Consumer.remote()

    @ray_tpu.remote
    def produce():
        return list(range(50_000))

    ref = c.consume.remote(produce.remote())
    # the intermediate ref was created inline and dropped immediately
    assert ray_tpu.get(ref, timeout=30) == 50_000


def test_actor_restart_releases_resources(ray_start_regular):
    # Regression: a restarted actor must not leak its resource allocation —
    # after kill, the CPU it held must be schedulable again.
    @ray_tpu.remote(num_cpus=1, max_restarts=1)
    class Holder:
        def ping(self):
            return "ok"

        def crash(self):
            import os

            os._exit(1)

    h = Holder.remote()
    assert ray_tpu.get(h.ping.remote(), timeout=30) == "ok"
    h.crash.remote()
    # wait for restart
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            assert ray_tpu.get(h.ping.remote(), timeout=5) == "ok"
            break
        except RayActorError:
            time.sleep(0.2)
    else:
        pytest.fail("actor did not restart")
    ray_tpu.kill(h)

    # all CPUs must come back once the actor is dead
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        avail = ray_tpu.available_resources().get("CPU", 0)
        total = ray_tpu.cluster_resources().get("CPU", 0)
        if avail == total:
            break
        time.sleep(0.2)
    assert ray_tpu.available_resources().get("CPU") == ray_tpu.cluster_resources().get("CPU")
