"""Attention kernels: Pallas flash (interpret mode on CPU) and ring
attention over the sp mesh axis must agree with the XLA reference path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import causal_attention, _xla_attention
from ray_tpu.ops.flash_attention import flash_attention


def _qkv(b=2, h=4, s=256, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return [jax.random.normal(k, (b, h, s, d), dtype) for k in ks]


def test_flash_forward_matches_xla():
    q, k, v = _qkv()
    ref = _xla_attention(q, k, v)
    out = flash_attention(q, k, v, block_q=64, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_gradients_match_xla():
    q, k, v = _qkv(b=1, h=2, s=128, d=64)
    w = jnp.cos(jnp.arange(64))

    def loss(attn):
        return lambda q, k, v: (attn(q, k, v) * w).sum()

    g_ref = jax.grad(loss(_xla_attention), argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss(lambda q, k, v: flash_attention(q, k, v, 64, 64)), argnums=(0, 1, 2))(
        q, k, v
    )
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=5e-5)


def test_flash_uneven_blocks_autoshrink():
    # seq 192 isn't divisible by 128: _pick_blocks must shrink to 64
    q, k, v = _qkv(s=192)
    ref = _xla_attention(q, k, v)
    out = flash_attention(q, k, v, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_causal_attention_auto_dispatch_small_seq():
    # tiny seq takes the XLA path; result identical either way
    q, k, v = _qkv(s=64)
    np.testing.assert_allclose(
        np.asarray(causal_attention(q, k, v, impl="auto")),
        np.asarray(_xla_attention(q, k, v)),
        atol=1e-6,
    )


def test_flash_sharded_matches_dense():
    """sp=1 multi-device mesh (dp=2, tp=2): the shard_map'd Pallas kernel
    must agree with dense attention, forward and gradients."""
    from jax.sharding import Mesh

    from ray_tpu.ops.flash_attention import flash_attention_sharded, flash_shardable

    devs = np.array(jax.devices()[:4]).reshape(2, 1, 2, 1)
    mesh = Mesh(devs, ("dp", "fsdp", "tp", "sp"))
    q, k, v = _qkv(b=2, h=4, s=128, d=32)
    assert flash_shardable(2, 4, mesh)
    assert not flash_shardable(3, 4, mesh)
    ref = _xla_attention(q, k, v)
    w = jnp.cos(jnp.arange(32))
    with mesh:
        out = jax.jit(lambda q, k, v: flash_attention_sharded(q, k, v, mesh))(q, k, v)
        g_sh = jax.jit(
            jax.grad(
                lambda q, k, v: (flash_attention_sharded(q, k, v, mesh) * w).sum(),
                argnums=(0, 1, 2),
            )
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    g_ref = jax.grad(lambda q, k, v: (_xla_attention(q, k, v) * w).sum(), argnums=(0, 1, 2))(
        q, k, v
    )
    for a, b in zip(g_ref, g_sh):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=5e-5)


def test_ring_attention_matches_dense():
    """sp=2 ring attention over the virtual CPU mesh == dense causal."""
    from jax.sharding import Mesh, PartitionSpec as P

    from ray_tpu.ops.ring_attention import ring_attention_sharded

    devs = np.array(jax.devices()[:8]).reshape(2, 1, 2, 2)
    mesh = Mesh(devs, ("dp", "fsdp", "tp", "sp"))
    q, k, v = _qkv(b=2, h=4, s=256, d=32)
    ref = _xla_attention(q, k, v)
    with mesh:
        out = jax.jit(lambda q, k, v: ring_attention_sharded(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_grads_match_dense():
    from jax.sharding import Mesh

    from ray_tpu.ops.ring_attention import ring_attention_sharded

    devs = np.array(jax.devices()[:4]).reshape(1, 1, 1, 4)
    mesh = Mesh(devs, ("dp", "fsdp", "tp", "sp"))
    q, k, v = _qkv(b=1, h=1, s=128, d=32)
    w = jnp.sin(jnp.arange(32))

    def ring_loss(q, k, v):
        return (ring_attention_sharded(q, k, v, mesh) * w).sum()

    def ref_loss(q, k, v):
        return (_xla_attention(q, k, v) * w).sum()

    with mesh:
        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=5e-5)


def test_gpt_forward_with_ring_attention_matches_single():
    """Full GPT fwd with sp=2 mesh (ring path) == sp=1 (flash/xla path)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu.models.gpt import GPTConfig, gpt_forward, gpt_init

    cfg = GPTConfig(
        vocab_size=256, seq_len=128, d_model=64, n_layers=2, n_heads=2, dtype="float32"
    )
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 256, jnp.int32)

    ref = gpt_forward(cfg, params, tokens)  # no mesh: dense path

    devs = np.array(jax.devices()[:8]).reshape(2, 1, 2, 2)
    mesh = Mesh(devs, ("dp", "fsdp", "tp", "sp"))
    with mesh:
        out = jax.jit(lambda p, t: gpt_forward(cfg, p, t, mesh))(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)
