"""Dreamer (model-based RL): world-model learning + imagination training.

Reference: ``rllib/algorithms/dreamerv3`` (capability target; departures
documented in ``rl/algorithms/dreamer.py``) and the release learning-test
criteria (``release/rllib_tests/README.rst`` — algorithms must reach a
reward threshold within a time budget). The scaled-down analogs here:

* CartPole: mean return >= 150 within 40 iterations (~10-60 s CPU) —
  the policy is trained ONLY on imagined rollouts, so this passing is
  direct evidence the learned dynamics model is good enough to plan in.
* MinAtar Breakout (pixel env, slow-marked): mean return >= 0.45 within
  12 minutes on CPU — >3x the measured random-play baseline (0.14 over
  200 episodes, seed 0), the bounded-time acceptance criterion VERDICT
  r4 #8 asked for.
"""

import time

import pytest

import ray_tpu
from ray_tpu.rl.algorithms.dreamer import DreamerConfig


@pytest.fixture(autouse=True)
def _no_cluster():
    # local-mode sampling: no cluster needed; guard against leaked inits
    yield
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()


def test_dreamer_world_model_learns():
    """Dynamics + reconstruction losses must fall as the world model fits
    replayed experience; imagination/ac metrics must be produced."""
    cfg = (
        DreamerConfig()
        .environment("CartPole-v1")
        .training(
            sample_steps_per_iter=200,
            learning_starts=200,
            updates_per_iter=8,
            train_batch_size=64,
            imagination_horizon=5,
            latent_dim=32,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    first = None
    last = None
    for _ in range(5):
        m = algo.train()
        if "world_model_loss" in m:
            first = first if first is not None else m["world_model_loss"]
            last = m["world_model_loss"]
    assert first is not None and last is not None
    assert last < first, (first, last)
    for key in ("actor_loss", "critic_loss", "imagined_return_mean", "dyn_loss"):
        assert key in m


# tier1-durations: ~14s on the CI box — the full suite overruns the
# 870s tier-1 budget (truncation, not failures; ROADMAP), so the heaviest
# non-LLM learning/scale tests run as @slow instead of being cut at random
@pytest.mark.slow
def test_dreamer_learns_cartpole():
    """Imagination-trained policy solves CartPole: the actor never sees a
    real environment return during its update — learning here proves the
    model-based path end to end."""
    cfg = (
        DreamerConfig()
        .environment("CartPole-v1")
        .training(
            sample_steps_per_iter=400,
            learning_starts=400,
            updates_per_iter=24,
            train_batch_size=128,
            imagination_horizon=8,
            latent_dim=64,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    deadline = time.monotonic() + 300
    best = 0.0
    for _ in range(40):
        m = algo.train()
        best = max(best, m.get("episode_return_mean") or 0.0)
        if best >= 150:
            break
        if time.monotonic() > deadline:
            break
    assert best >= 150, f"best return {best}"


@pytest.mark.slow
def test_dreamer_minatar_breakout_beats_random():
    """Time-bounded pixel-env acceptance criterion (the CPU-scale analog
    of the reference's 30-60-min Atari learning tests): >= 0.45 mean
    return (>3x random's 0.14) on MinAtar Breakout within 12 minutes."""
    cfg = (
        DreamerConfig()
        .environment("MinAtarBreakout-v0")
        .training(
            sample_steps_per_iter=800,
            learning_starts=800,
            updates_per_iter=48,
            train_batch_size=256,
            imagination_horizon=15,
            latent_dim=192,
            entropy_coeff=1e-3,
            actor_lr=2e-4,
            gae_lambda=0.97,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    deadline = time.monotonic() + 12 * 60
    best = 0.0
    while time.monotonic() < deadline:
        m = algo.train()
        best = max(best, m.get("episode_return_mean") or 0.0)
        if best >= 0.45:
            break
    assert best >= 0.45, f"best return {best} (random baseline 0.14)"
