"""Streaming generator returns: num_returns="streaming" yields per-item
ObjectRefs while the task runs.

Reference: ObjectRefGenerator + streaming-generator reporting
(``python/ray/_raylet.pyx:1230``) and the streaming return bookkeeping in
``src/ray/core_worker/task_manager.cc`` — items become objects as they are
produced, consumers iterate with backpressure, mid-stream errors surface at
the point of consumption."""

import time

import pytest

import ray_tpu


def test_roundtrip_and_laziness(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * i

    g = gen.remote(5)
    assert isinstance(g, ray_tpu.ObjectRefGenerator)
    vals = [ray_tpu.get(ref, timeout=30) for ref in g]
    assert vals == [0, 1, 4, 9, 16]


def test_items_arrive_before_task_completes(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def slow_tail():
        yield "first"
        time.sleep(5.0)
        yield "last"

    g = slow_tail.remote()
    t0 = time.monotonic()
    first = ray_tpu.get(next(iter(g)), timeout=30)
    assert first == "first"
    # the first item must arrive long before the producer finishes
    assert time.monotonic() - t0 < 4.0
    g.close()


def test_error_mid_stream(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def boom():
        yield 1
        yield 2
        raise ValueError("stream exploded")

    g = boom.remote()
    it = iter(g)
    assert ray_tpu.get(next(it), timeout=30) == 1
    assert ray_tpu.get(next(it), timeout=30) == 2
    with pytest.raises(ValueError, match="stream exploded"):
        next(it)


def test_function_error_before_first_yield(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def notgen():
        return 42  # not an iterable -> typed error at consumption

    with pytest.raises(TypeError, match="streaming"):
        next(iter(notgen.remote()))


def test_backpressure_bounds_producer(ray_start_regular):
    @ray_tpu.remote
    class Progress:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1

        def value(self):
            return self.n

    p = Progress.options(name="prog").remote()
    ray_tpu.get(p.value.remote(), timeout=30)

    @ray_tpu.remote(num_returns="streaming")
    def firehose():
        import ray_tpu as rt

        prog = rt.get_actor("prog")
        for i in range(100):
            prog.bump.remote()
            yield i

    from ray_tpu._private.config import GLOBAL_CONFIG

    cap = GLOBAL_CONFIG.streaming_backpressure_items
    g = firehose.remote()
    it = iter(g)
    ray_tpu.get(next(it), timeout=30)  # consume exactly one item
    time.sleep(1.0)  # give an unbounded producer time to run away
    produced = ray_tpu.get(p.value.remote(), timeout=30)
    # consumed 1, so the producer must be paused within its window
    assert produced <= 1 + cap + 2, f"producer ran {produced} items ahead"
    # drain: everything still arrives in order
    rest = [ray_tpu.get(r, timeout=30) for r in it]
    assert rest == list(range(1, 100))


def test_dispose_cancels_running_producer(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def endless():
        i = 0
        while True:
            yield i
            i += 1

    g = endless.remote()
    it = iter(g)
    assert ray_tpu.get(next(it), timeout=30) == 0
    g.close()  # consumer walks away -> producer must be cancelled
    from ray_tpu._private.runtime import get_ctx

    head = get_ctx().head
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        with head.lock:
            if not head.tasks:
                break
        time.sleep(0.1)
    with head.lock:
        assert not head.tasks, "producer still running after dispose"


def test_data_pipeline_starts_before_read_finishes(ray_start_regular):
    """A Data map stage consumes a streaming read upstream: the first
    bundle flows downstream while the datasource is still producing
    (reference: read tasks as streaming generators feeding the executor)."""
    import numpy as np

    import ray_tpu.data as rdata
    from ray_tpu.data.datasource import BlockMetadata, Datasource, ReadTask

    def slow_blocks():
        yield {"x": np.arange(10)}
        time.sleep(6.0)  # tail of the read: must NOT gate the first batch
        yield {"x": np.arange(10, 20)}

    class SlowSource(Datasource):
        def get_read_tasks(self, parallelism):
            meta = BlockMetadata(num_rows=None, size_bytes=None, input_files=None)
            return [ReadTask(slow_blocks, meta)]

    ds = rdata.read_datasource(SlowSource()).map(lambda row: {"x": row["x"] + 1})
    t0 = time.monotonic()
    it = ds.iter_batches(batch_size=10)
    first = next(iter(it))
    assert time.monotonic() - t0 < 5.0, "first batch waited for the whole read"
    assert list(first["x"])[:3] == [1, 2, 3]


def test_sync_actor_method_streams(ray_start_regular):
    @ray_tpu.remote
    class Chunker:
        def chunks(self, n):
            for i in range(n):
                yield f"chunk-{i}"

    c = Chunker.remote()
    g = c.chunks.options(num_returns="streaming").remote(3)
    assert [ray_tpu.get(r, timeout=30) for r in g] == ["chunk-0", "chunk-1", "chunk-2"]


def test_async_actor_method_streams(ray_start_regular):
    @ray_tpu.remote
    class AsyncChunker:
        async def chunks(self, n):
            import asyncio

            for i in range(n):
                await asyncio.sleep(0.01)
                yield i * 10

        async def ping(self):
            return "pong"

    c = AsyncChunker.remote()
    g = c.chunks.options(num_returns="streaming").remote(4)
    assert [ray_tpu.get(r, timeout=30) for r in g] == [0, 10, 20, 30]
    # loop stayed serviceable while the stream ran
    assert ray_tpu.get(c.ping.remote(), timeout=30) == "pong"
