"""Engine-level robustness: token-exact resume, watchdog, shedding.

The serve-plane chaos suite (tests/test_serve_chaos.py) proves these
survive real SIGKILLs through the full serve stack; this file pins the
underlying engine primitives (RESILIENCE.md):

* ``submit(resume_tokens=...)`` continues a partial generation
  TOKEN-IDENTICALLY — greedy and seeded sampling, at every cut point —
  because per-token PRNG keys derive from (seed, absolute output index),
  never from where a window or a failover boundary fell;
* the watchdog reaps cancelled/deadline-blown requests with the engine
  lock when it can, and unblocks their stream consumers WITHOUT it when
  the step loop is wedged holding it;
* the KV-pool ledger audit catches leaked, duplicated, and orphaned
  blocks;
* deadline-aware admission sheds doomed work with ``OverloadedError``
  (+ retry_after_s) instead of queueing it;
* ``stream_tokens`` timeouts carry the stall diagnosis
  (``EngineStalledError``).
"""

import queue
import threading
import time

import numpy as np
import pytest

import jax

from ray_tpu.exceptions import OverloadedError
from ray_tpu.llm import (
    EngineConfig,
    EngineStalledError,
    EngineWatchdog,
    LLMEngine,
    SamplingParams,
)
from ray_tpu.models.gptj import GPTJConfig, gptj_init

TINY = GPTJConfig(
    vocab_size=128, seq_len=64, d_model=32, n_layers=2, n_heads=2,
    rotary_dim=8, dtype="float32", remat=False, attn_impl="xla",
    fused_loss=False,
)


@pytest.fixture(scope="module")
def tiny_params():
    return gptj_init(jax.random.PRNGKey(0), TINY)


def _engine(params, **kw):
    defaults = dict(
        max_slots=3, num_blocks=32, block_size=4, max_blocks_per_seq=12,
        prefill_chunk=8,
    )
    defaults.update(kw)
    return LLMEngine(TINY, params, EngineConfig(**defaults))


@pytest.fixture(scope="module")
def shared_engine(tiny_params):
    """One engine for the resume-identity tests (fresh engines re-jit;
    resume correctness is host-side bookkeeping, so sharing is safe as
    long as each test leaves it drained)."""
    return _engine(tiny_params)


def _drain(eng, req):
    """Step the engine until ``req`` finishes; returns the streamed tokens
    (only what was produced AFTER submission — a resumed prefix is not
    re-streamed)."""
    got = []
    deadline = time.time() + 60
    while not req.finished:
        eng.step()
        assert time.time() < deadline, "engine made no progress"
    while True:
        try:
            kind, val = req.stream.get_nowait()
        except queue.Empty:
            break
        if kind == "token":
            got.append(val)
        else:
            break
    return got


PROMPT = [5, 6, 7, 5, 6, 7, 5, 6, 7]

GREEDY = SamplingParams(max_tokens=20)
SAMPLED = SamplingParams(max_tokens=20, temperature=0.8, top_k=5, top_p=0.9,
                         seed=1234)


class TestResumeTokens:
    @pytest.mark.parametrize("params", [GREEDY, SAMPLED],
                             ids=["greedy", "sampled"])
    def test_resume_is_token_identical_at_every_cut(self, shared_engine, params):
        """The failover invariant: resuming from ANY delivered prefix
        reproduces the unkilled run exactly — greedy and seeded sampling."""
        eng = shared_engine
        full = eng.generate(PROMPT, params)
        assert len(full) == params.max_tokens
        for cut in (0, 1, 7, params.max_tokens - 1, params.max_tokens):
            req = eng.submit(PROMPT, params, resume_tokens=full[:cut])
            got = _drain(eng, req)
            assert full[:cut] + got == full, f"cut={cut}"
            # the resumed prefix is never re-streamed
            assert len(got) == params.max_tokens - cut

    def test_resume_after_final_token_finishes_immediately(self, shared_engine):
        """Replica died between the last token and the done sentinel: the
        resume must finish without touching the scheduler."""
        eng = shared_engine
        full = eng.generate(PROMPT, GREEDY)
        before = eng.scheduler.finish_count
        req = eng.submit(PROMPT, GREEDY, resume_tokens=full)
        assert req.finished and req.finish_reason == "length"
        assert req.stream.get_nowait() == ("done", "length")
        assert eng.scheduler.finish_count == before  # never entered

    def test_resume_on_delivered_stop_token(self, shared_engine):
        eng = shared_engine
        sp = SamplingParams(max_tokens=20, stop_token_ids=(114,))
        full = eng.generate(PROMPT, sp)
        assert full[-1] == 114
        req = eng.submit(PROMPT, sp, resume_tokens=full)
        assert req.finished and req.finish_reason == "stop"

    def test_resume_survives_preemption(self, tiny_params):
        """A resumed request that then gets PREEMPTED re-prefills
        prompt + resumed + new tokens and still matches the reference —
        the two recovery mechanisms compose."""
        eng = _engine(tiny_params, max_slots=2, num_blocks=14,
                      max_blocks_per_seq=10)
        full = eng.generate(PROMPT, GREEDY)
        # resume, then saturate the pool so the resumed request gets evicted
        req = eng.submit(PROMPT, GREEDY, resume_tokens=full[:6])
        rival = eng.submit(_rand_prompt(8), SamplingParams(max_tokens=20))
        got = _drain(eng, req)
        _drain(eng, rival)
        assert full[:6] + got == full
        assert eng.pool.audit()["ok"]

    def test_resume_validation(self, shared_engine):
        with pytest.raises(ValueError, match="resume_tokens"):
            shared_engine.submit(
                PROMPT, SamplingParams(max_tokens=4), resume_tokens=[1] * 5
            )


def _rand_prompt(n, seed=3):
    return list(np.random.RandomState(seed).randint(0, TINY.vocab_size, n))


class TestWatchdog:
    def test_reaps_deadline_and_cancel_with_lock(self, tiny_params):
        """Nobody driving step(): the watchdog alone frees slots/blocks of
        doomed requests through the scheduler."""
        eng = _engine(tiny_params)
        wd = EngineWatchdog(eng, stall_deadline_s=30.0)
        r1 = eng.submit(PROMPT, SamplingParams(max_tokens=4), deadline_s=0.0)
        r2 = eng.submit(PROMPT, SamplingParams(max_tokens=4))
        eng.cancel(r2.id)
        info = wd.check_once()
        assert info["reaped"] == 2 and info["unblocked"] == 0
        assert r1.finished and r1.finish_reason == "deadline"
        assert r2.finished and r2.finish_reason == "cancelled"
        assert info["audit"]["ok"]
        assert eng.pool.num_used_blocks == 0  # blocks came back

    def test_wedged_step_unblocks_consumers(self, tiny_params):
        """The step loop is stuck holding the engine lock: the watchdog
        cannot touch scheduler state, but stream consumers of
        deadline-blown requests still get their done sentinel."""
        eng = _engine(tiny_params)
        wd = EngineWatchdog(eng, stall_deadline_s=0.05, lock_timeout_s=0.01)
        req = eng.submit(PROMPT, SamplingParams(max_tokens=4), deadline_s=0.01)
        time.sleep(0.08)
        eng._lock.acquire()  # the wedge
        try:
            info = wd.check_once()
        finally:
            eng._lock.release()
        assert info["stalled"] and info["unblocked"] == 1
        assert req.stream.get_nowait() == ("done", "deadline")
        # a second tick must not double-unblock the same request
        eng._lock.acquire()
        try:
            assert wd.check_once()["unblocked"] == 0
        finally:
            eng._lock.release()

    def test_stall_detection_one_event_per_episode(self, tiny_params):
        eng = _engine(tiny_params)
        wd = EngineWatchdog(eng, stall_deadline_s=0.05)
        eng.submit(PROMPT, SamplingParams(max_tokens=4))
        eng._beat = (time.monotonic() - 1.0, 1)  # fake a wedged step
        assert wd.check_once()["stalled"]
        assert wd.check_once()["stalled"]
        assert wd.stall_count == 1  # episode counted once
        # progress clears the episode; a NEW wedge counts again
        eng.step()
        assert not wd.check_once()["stalled"]
        eng._beat = (time.monotonic() - 1.0, 1)
        wd.check_once()
        assert wd.stall_count == 2

    def test_idle_engine_never_stalls(self, tiny_params):
        eng = _engine(tiny_params)
        wd = EngineWatchdog(eng, stall_deadline_s=0.0)
        info = wd.check_once()
        assert not info["stalled"] and info["pending"] == 0

    def test_leak_audit_detects_orphans_and_duplicates(self, tiny_params):
        eng = _engine(tiny_params)
        wd = EngineWatchdog(eng)
        assert wd.check_once()["audit"]["ok"]
        # an owner with no live request = leaked blocks
        eng.pool.allocate("ghost", 8)
        audit = wd.check_once()["audit"]
        assert not audit["ok"] and audit["orphans"] == ["ghost"]
        assert wd.leak_count == 1
        eng.pool.free("ghost")
        assert wd.check_once()["audit"]["ok"]
        # ledger corruption: the same block on the free list twice
        eng.pool._free.append(eng.pool._free[-1])
        audit = eng.pool.audit()
        assert audit["duplicates"] and audit["missing"] < 0 and not audit["ok"]

    def test_watchdog_thread_lifecycle(self, tiny_params):
        eng = _engine(tiny_params)
        wd = eng.start_watchdog()
        assert wd.is_alive()
        assert eng.start_watchdog() is wd  # idempotent
        wd.stop()
        assert not wd.is_alive()


class TestShedding:
    def test_doomed_deadline_is_shed_with_retry_after(self, tiny_params):
        eng = _engine(tiny_params)
        eng._rate = 50.0  # measured service rate: 50 tokens/s
        for _ in range(3):
            eng.submit(PROMPT, SamplingParams(max_tokens=20))
        # backlog is 60 promised tokens ≈ 1.2s; a 0.1s deadline is doomed
        with pytest.raises(OverloadedError) as ei:
            eng.submit(PROMPT, SamplingParams(max_tokens=20), deadline_s=0.1)
        assert ei.value.retry_after_s > 0
        # ...but a generous deadline is admitted
        req = eng.submit(PROMPT, SamplingParams(max_tokens=20), deadline_s=60.0)
        assert req.state == "waiting"

    def test_no_rate_evidence_never_sheds(self, tiny_params):
        eng = _engine(tiny_params)
        assert eng._rate == 0.0
        req = eng.submit(PROMPT, SamplingParams(max_tokens=20), deadline_s=0.001)
        assert req in list(eng.scheduler.waiting)

    def test_no_deadline_never_sheds(self, tiny_params):
        eng = _engine(tiny_params)
        eng._rate = 1.0
        for _ in range(4):
            eng.submit(PROMPT, SamplingParams(max_tokens=20))
        assert eng.scheduler.num_waiting == 4

    def test_shed_disabled_by_config(self, tiny_params):
        eng = _engine(tiny_params, shed=False)
        eng._rate = 50.0
        for _ in range(3):
            eng.submit(PROMPT, SamplingParams(max_tokens=20))
        req = eng.submit(PROMPT, SamplingParams(max_tokens=20), deadline_s=0.01)
        assert not req.finished

    def test_service_rate_tracks_generation_and_resets_idle(self, tiny_params):
        eng = _engine(tiny_params)
        # sustained generation (> the 0.5s sampling window) measures a rate
        deadline = time.time() + 30
        while eng.stats()["service_rate_tokens_per_s"] <= 0:
            eng.generate(PROMPT, SamplingParams(max_tokens=20))
            assert time.time() < deadline, "rate never measured"
        # going idle RESETS it (no evidence ≠ slow): the next burst's first
        # request must not be shed on a stale decayed rate. Two idle
        # sampling windows: the first still counts the burst's tail tokens,
        # the second sees zero generation with no work and zeroes the rate.
        for _ in range(2):
            time.sleep(0.6)
            eng.step()
        assert eng.stats()["service_rate_tokens_per_s"] == 0.0
        req = eng.submit(PROMPT, SamplingParams(max_tokens=4), deadline_s=0.5)
        assert not req.finished  # admitted, not shed

    def test_empty_engine_never_sheds_despite_stale_rate(self, tiny_params):
        eng = _engine(tiny_params)
        eng._rate = 0.001  # pathologically stale-low rate, zero backlog
        req = eng.submit(PROMPT, SamplingParams(max_tokens=20), deadline_s=0.5)
        assert not req.finished  # no backlog -> no shedding evidence


class TestEngineStalledError:
    def test_timeout_carries_diagnosis(self, tiny_params):
        eng = _engine(tiny_params)
        req = eng.submit(PROMPT, SamplingParams(max_tokens=4))
        with pytest.raises(EngineStalledError) as ei:
            list(eng.stream_tokens(req, timeout=0.05))
        err = ei.value
        assert isinstance(err, TimeoutError)  # old catch sites keep working
        assert err.queue_depth >= 1
        assert err.last_step_age_s >= 0.0
        assert 0.0 <= err.kv_utilization <= 1.0
        assert "queue_depth" in str(err)

    def test_pickles_with_diagnosis(self, tiny_params):
        import pickle

        err = EngineStalledError(
            "x", last_step_age_s=1.5, queue_depth=3, kv_utilization=0.5
        )
        back = pickle.loads(pickle.dumps(err))
        assert isinstance(back, EngineStalledError)
        assert back.last_step_age_s == 1.5 and back.queue_depth == 3

    def test_healthy_stream_unaffected(self, tiny_params):
        eng = _engine(tiny_params)
        stop = threading.Event()
        t = threading.Thread(target=eng.run_loop, args=(stop,), daemon=True)
        t.start()
        try:
            req = eng.submit(PROMPT, SamplingParams(max_tokens=8))
            toks = list(eng.stream_tokens(req, timeout=30))
            assert len(toks) == 8
        finally:
            stop.set()
            t.join(timeout=5)
