"""RL017's runtime twin (raylint v4, ISSUE 15).

Three layers, mirroring the donation twin (`test_llm_donation.py`) shape:

* **The failure mode is real:** a fixture with exactly the bug shape the
  static rule fires on — a read-modify-write counter touched from many
  threads with no lock — demonstrably CORRUPTS (loses updates) on this
  interpreter, and the locked fix is exact under the same hammer. If a
  future interpreter makes unlocked RMW exact (per-object locks, true
  GIL removal with atomics), the probe fails loudly and the rule's
  premise gets re-examined instead of silently rotting.
* **The static twin agrees:** raylint RL017 fires on the racy fixture's
  source and stays quiet on the locked fix — the lint rule and the
  runtime corruption point at the same line.
* **Declared lock-free designs hold:** the repo's LOCKFREE declarations
  are verified against the REAL sources through the thread model
  (`test_obs_hotpath.py` extends the same contract) — and the structures
  they cover (per-thread rings, counter cells) survive the 8-thread
  hammers in `test_obs_hotpath.py`.
"""

import textwrap
import threading
import time

N_THREADS = 8
PER = 4000


class RacyWindow:
    """The RL017 bug shape: unguarded read-modify-write credit counter."""

    def __init__(self):
        self.credits = 0

    def bump(self):
        v = self.credits
        # widen the read->write window the way real code does (a dict
        # lookup, an allocation) so the loss shows in bounded iterations
        if v % 64 == 0:
            time.sleep(0)
        self.credits = v + 1


class LockedWindow:
    def __init__(self):
        self._lock = threading.Lock()
        self.credits = 0

    def bump(self):
        with self._lock:
            self.credits += 1


def _hammer(win) -> int:
    threads = [
        threading.Thread(target=lambda: [win.bump() for _ in range(PER)])
        for _ in range(N_THREADS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive()
    return win.credits


def test_unlocked_rmw_actually_corrupts():
    """The probe: at least one of a few rounds must LOSE updates without
    the lock — this is the premise RL017's aug/mutate focus rests on.
    (A single round is overwhelmingly likely to lose on CPython; the
    retry keeps a freak all-exact run from flaking the suite.)"""
    lost = False
    for _ in range(5):
        total = _hammer(RacyWindow())
        assert total <= N_THREADS * PER
        if total < N_THREADS * PER:
            lost = True
            break
    assert lost, (
        "unguarded read-modify-write was exact across 5 hammer rounds — "
        "this interpreter may have atomic attribute RMW; re-examine "
        "RL017's premise before trusting this probe"
    )


def test_locked_counter_exact_under_hammer():
    for _ in range(2):
        assert _hammer(LockedWindow()) == N_THREADS * PER


def test_static_twin_fires_on_the_racy_shape(tmp_path):
    """raylint RL017 and the runtime corruption point at the same code:
    the racy fixture (spawned threads hammering the unguarded counter)
    fires; the locked fix lints clean."""
    from ray_tpu._lint import run_paths

    racy = textwrap.dedent(
        """
        import threading

        class RacyWindow:
            def __init__(self):
                self.credits = 0
                self._a = threading.Thread(target=self._bump, daemon=True)
                self._b = threading.Thread(target=self._bump2, daemon=True)

            def _bump(self):
                self.credits += 1

            def _bump2(self):
                self.credits += 1
        """
    )
    f = tmp_path / "racy.py"
    f.write_text(racy)
    vs = [v for v in run_paths([str(f)]) if v.rule == "RL017"]
    assert vs and "RacyWindow.credits" in vs[0].message

    fixed = racy.replace(
        "self.credits = 0",
        "self._lock = threading.Lock()\n        self.credits = 0",
    ).replace(
        "        self.credits += 1",
        "        with self._lock:\n            self.credits += 1",
    )
    g = tmp_path / "fixed.py"
    g.write_text(fixed)
    assert not [v for v in run_paths([str(g)]) if v.rule == "RL017"]


def test_gil_atomic_container_ops_exact_under_hammer():
    """The ': atomic' LOCKFREE qualifier's premise: single-operation dict
    stores/pops and deque appends from N threads lose nothing — each op
    is one GIL-atomic bytecode-level operation (what the declared
    designs — _io_conns, task_threads, _rings — rely on)."""
    d: dict = {}
    from collections import deque

    ring: deque = deque()

    def work(k):
        for i in range(PER):
            d[(k, i)] = i       # plain store
            ring.append((k, i))  # deque append

    threads = [threading.Thread(target=work, args=(k,)) for k in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert len(d) == N_THREADS * PER
    assert len(ring) == N_THREADS * PER
