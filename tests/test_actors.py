"""Actor tests (modeled on the reference's ``python/ray/tests/test_actor.py``
family: ordering, state, named actors, restarts, kill)."""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import RayActorError


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.v = start

    def inc(self, by=1):
        self.v += by
        return self.v

    def value(self):
        return self.v

    def crash(self):
        import os

        os._exit(1)


def test_actor_basic(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    assert ray_tpu.get(c.inc.remote(5)) == 6
    assert ray_tpu.get(c.value.remote()) == 6


def test_actor_init_args(ray_start_regular):
    c = Counter.remote(100)
    assert ray_tpu.get(c.value.remote()) == 100


def test_actor_method_ordering(ray_start_regular):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(50)]
    assert ray_tpu.get(refs) == list(range(1, 51))


def test_actor_state_isolated(ray_start_regular):
    a, b = Counter.remote(), Counter.remote()
    ray_tpu.get(a.inc.remote())
    assert ray_tpu.get(b.value.remote()) == 0


def test_named_actor(ray_start_regular):
    # Keep the original handle alive: like the reference, a non-detached named
    # actor is killed once every handle goes out of scope.
    c = Counter.options(name="global_counter").remote()
    h = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(h.inc.remote()) == 1
    del c


def test_named_actor_missing(ray_start_regular):
    with pytest.raises(ValueError):
        ray_tpu.get_actor("no_such_actor")


def test_actor_init_error_propagates(ray_start_regular):
    @ray_tpu.remote
    class Bad:
        def __init__(self):
            raise RuntimeError("init failed")

        def m(self):
            return 1

    b = Bad.remote()
    with pytest.raises((RuntimeError, RayActorError)):
        ray_tpu.get(b.m.remote())


def test_actor_crash_no_restart(ray_start_regular):
    c = Counter.remote()
    ray_tpu.get(c.inc.remote())
    c.crash.remote()
    with pytest.raises(RayActorError):
        ray_tpu.get(c.value.remote(), timeout=30)


def test_actor_restart(ray_start_regular):
    c = Counter.options(max_restarts=1).remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    c.crash.remote()
    # wait for restart; state resets (fresh __init__)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            v = ray_tpu.get(c.value.remote(), timeout=5)
            assert v == 0
            break
        except RayActorError:
            time.sleep(0.2)
    else:
        pytest.fail("actor did not restart")


def test_ray_kill(ray_start_regular):
    c = Counter.options(max_restarts=5).remote()
    ray_tpu.get(c.inc.remote())
    ray_tpu.kill(c)
    with pytest.raises(RayActorError):
        ray_tpu.get(c.value.remote(), timeout=30)


def test_actor_handle_passed_to_task(ray_start_regular):
    c = Counter.remote()

    @ray_tpu.remote
    def use(handle):
        return ray_tpu.get(handle.inc.remote())

    assert ray_tpu.get(use.remote(c)) == 1
    assert ray_tpu.get(c.value.remote()) == 1


def test_max_concurrency(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Parallel:
        def block(self, t):
            time.sleep(t)
            return 1

    p = Parallel.remote()
    ray_tpu.get(p.block.remote(0.0))  # wait for actor bring-up before timing
    start = time.monotonic()
    refs = [p.block.remote(1.0) for _ in range(4)]
    ray_tpu.get(refs)
    assert time.monotonic() - start < 3.5  # would be >=4s if serialized


def test_method_num_returns(ray_start_regular):
    @ray_tpu.remote
    class M:
        @ray_tpu.method(num_returns=2)
        def two(self):
            return 1, 2

    m = M.remote()
    a, b = m.two.remote()
    assert ray_tpu.get([a, b]) == [1, 2]


def test_actor_pool(ray_start_regular):
    from ray_tpu.util.actor_pool import ActorPool

    @ray_tpu.remote
    class W:
        def f(self, x):
            return x * 2

    pool = ActorPool([W.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.f.remote(v), [1, 2, 3, 4]))
    assert out == [2, 4, 6, 8]
