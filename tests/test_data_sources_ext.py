"""Datasource breadth: webdataset shards, gated Mongo/BigQuery, ray:// client.

Reference counterparts: ``python/ray/data/datasource/webdataset_datasource.py``,
``mongo_datasource.py``, ``bigquery_datasource.py``; ``ray://`` client mode
(``python/ray/util/client/``).
"""

import json
import tarfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


def _make_shard(path, n=4):
    with tarfile.open(path, "w") as tf:
        for i in range(n):
            for ext, payload in (
                ("txt", f"caption {i}".encode()),
                ("cls", str(i % 2).encode()),
                ("json", json.dumps({"idx": i}).encode()),
            ):
                import io

                info = tarfile.TarInfo(name=f"sample{i:04d}.{ext}")
                info.size = len(payload)
                tf.addfile(info, io.BytesIO(payload))


class TestWebDataset:
    def test_read_samples(self, ray_start_regular, tmp_path):
        shard = str(tmp_path / "data-0000.tar")
        _make_shard(shard, n=4)
        ds = rdata.read_webdataset(shard)
        rows = ds.take_all()
        assert len(rows) == 4
        assert rows[0]["txt"] == "caption 0"
        assert rows[0]["cls"] in (0, 1)
        assert rows[1]["json"]["idx"] == 1
        assert rows[2]["__key__"] == "sample0002"

    def test_multiple_shards_parallel(self, ray_start_regular, tmp_path):
        for i in range(3):
            _make_shard(str(tmp_path / f"data-{i:04d}.tar"), n=2)
        ds = rdata.read_webdataset(str(tmp_path / "data-*.tar"), parallelism=3)
        assert ds.count() == 6

    def test_no_decode(self, ray_start_regular, tmp_path):
        shard = str(tmp_path / "raw.tar")
        _make_shard(shard, n=1)
        rows = rdata.read_webdataset(shard, decode=False).take_all()
        assert rows[0]["txt"] == b"caption 0"


class TestGatedSources:
    def test_mongo_requires_pymongo(self):
        pytest.importorskip("ray_tpu")
        try:
            import pymongo  # noqa: F401

            pytest.skip("pymongo installed; gating not exercised")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="pymongo"):
            rdata.read_mongo("mongodb://x", "db", "coll")

    def test_bigquery_requires_client(self):
        try:
            from google.cloud import bigquery  # noqa: F401

            pytest.skip("bigquery installed; gating not exercised")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="bigquery"):
            rdata.read_bigquery("proj", query="select 1")


class TestRayClientScheme:
    def test_ray_scheme_attaches_over_tcp(self):
        """ray://host:port behaves as client mode against a live head."""
        import os
        import subprocess
        import sys

        # both sides must share the cluster secret (resolve_authkey)
        key = os.urandom(16).hex()
        env = dict(os.environ, RAY_TPU_AUTHKEY=key)
        # head in a separate process serving TCP
        script = (
            "import ray_tpu, time;"
            "info = ray_tpu.init(num_cpus=2);"
            "from ray_tpu._private.runtime import get_ctx;"
            "head = get_ctx().head;"
            "h, p = head.listen_tcp('127.0.0.1', 0);"
            "print(f'ADDR {h}:{p}', flush=True);"
            "time.sleep(60)"
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", script], stdout=subprocess.PIPE, text=True, env=env
        )
        os.environ["RAY_TPU_AUTHKEY"] = key
        try:
            line = proc.stdout.readline()
            assert line.startswith("ADDR"), line
            addr = line.split()[1]
            ray_tpu.init(address=f"ray://{addr}")
            try:

                @ray_tpu.remote
                def f(x):
                    return x * 7

                assert ray_tpu.get(f.remote(6), timeout=60) == 42
            finally:
                ray_tpu.shutdown()
        finally:
            os.environ.pop("RAY_TPU_AUTHKEY", None)
            proc.terminate()
            proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# round 5: long-tail sources (datasource_ext.py — VERDICT r4 #9)
# ---------------------------------------------------------------------------


def _zigzag(n: int) -> bytes:
    """Independent avro varint encoder for the reader round-trip (written
    from the spec, not from the module under test)."""
    u = (n << 1) ^ (n >> 63) if n < 0 else n << 1
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _avro_file(rows, deflate=False) -> bytes:
    """Minimal OCF writer for {"a": long, "b": string} records."""
    import zlib

    schema = {
        "type": "record",
        "name": "R",
        "fields": [{"name": "a", "type": "long"}, {"name": "b", "type": "string"}],
    }
    sj = json.dumps(schema).encode()
    codec = b"deflate" if deflate else b"null"
    sync = b"S" * 16
    head = b"Obj\x01"
    head += _zigzag(2)  # metadata map: 2 entries
    head += _zigzag(len(b"avro.schema")) + b"avro.schema" + _zigzag(len(sj)) + sj
    head += _zigzag(len(b"avro.codec")) + b"avro.codec" + _zigzag(len(codec)) + codec
    head += _zigzag(0) + sync
    payload = b""
    for r in rows:
        b = r["b"].encode()
        payload += _zigzag(r["a"]) + _zigzag(len(b)) + b
    if deflate:
        comp = zlib.compressobj(wbits=-15)
        payload = comp.compress(payload) + comp.flush()
    return head + _zigzag(len(rows)) + _zigzag(len(payload)) + payload + sync


@pytest.mark.parametrize("deflate", [False, True])
def test_read_avro_roundtrip(ray_start_regular, tmp_path, deflate):
    rows = [{"a": i * 7 - 3, "b": f"row-{i}"} for i in range(20)]
    p = tmp_path / "data.avro"
    p.write_bytes(_avro_file(rows, deflate=deflate))
    out = rdata.read_avro(str(p)).take_all()
    assert out == rows


def test_read_orc_roundtrip(ray_start_regular, tmp_path):
    import pyarrow as pa
    from pyarrow import orc

    table = pa.table({"x": list(range(10)), "y": [f"s{i}" for i in range(10)]})
    p = tmp_path / "data.orc"
    orc.write_table(table, str(p))
    out = rdata.read_orc(str(p)).take_all()
    assert [r["x"] for r in out] == list(range(10))
    sub = rdata.read_orc(str(p), columns=["y"]).take_all()
    assert set(sub[0]) == {"y"}


def test_read_feather_roundtrip(ray_start_regular, tmp_path):
    import pyarrow as pa
    import pyarrow.feather as feather

    table = pa.table({"v": [1.5, 2.5, 3.5]})
    p = tmp_path / "data.feather"
    feather.write_feather(table, str(p))
    out = rdata.read_feather(str(p)).take_all()
    assert [r["v"] for r in out] == [1.5, 2.5, 3.5]


def test_read_audio_wav(ray_start_regular, tmp_path):
    import wave

    import numpy as np

    p = tmp_path / "tone.wav"
    samples = (np.sin(np.linspace(0, 440, 8000)) * 32767).astype(np.int16)
    with wave.open(str(p), "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(8000)
        w.writeframes(samples.tobytes())
    out = rdata.read_audio(str(p)).take_all()
    assert out[0]["sample_rate"] == 8000
    assert out[0]["amplitude"].shape == (8000, 1)
    assert out[0]["amplitude"][:100, 0].tolist() == samples[:100].tolist()


def test_read_xml(ray_start_regular, tmp_path):
    p = tmp_path / "rows.xml"
    p.write_text(
        "<root><item id='1'><name>ann</name><age>30</age></item>"
        "<item id='2'><name>bo</name><age>40</age></item></root>"
    )
    out = rdata.read_xml(str(p), record_tag="item").take_all()
    assert out == [
        {"id": "1", "name": "ann", "age": "30"},
        {"id": "2", "name": "bo", "age": "40"},
    ]


def test_read_delta_log_replay(ray_start_regular, tmp_path):
    import pyarrow as pa
    from pyarrow import parquet as pq

    # build a delta table by hand: v0 adds two files, v1 removes one and
    # adds a third -> live set is files 1 and 2
    for i in range(3):
        pq.write_table(pa.table({"v": [i * 10, i * 10 + 1]}), str(tmp_path / f"part-{i}.parquet"))
    log = tmp_path / "_delta_log"
    log.mkdir()
    (log / "00000000000000000000.json").write_text(
        json.dumps({"add": {"path": "part-0.parquet"}}) + "\n"
        + json.dumps({"add": {"path": "part-1.parquet"}}) + "\n"
    )
    (log / "00000000000000000001.json").write_text(
        json.dumps({"remove": {"path": "part-0.parquet"}}) + "\n"
        + json.dumps({"add": {"path": "part-2.parquet"}}) + "\n"
    )
    out = sorted(r["v"] for r in rdata.read_delta(str(tmp_path)).take_all())
    assert out == [10, 11, 20, 21]


def test_read_clickhouse_fake_transport(ray_start_regular):
    def transport(url, body):
        # runs inside the read worker: assert THERE (a driver-side list
        # would never see the worker's append)
        q = body.decode()
        assert "FORMAT JSONEachRow" in q and q.count("FORMAT") == 1, q
        assert url == "http://ch:8123"
        return b'{"a": 1, "b": "x"}\n{"a": 2, "b": "y"}\n'

    out = rdata.read_clickhouse(
        "http://ch:8123", "SELECT a, b FROM t;", transport=transport
    ).take_all()
    assert out == [{"a": 1, "b": "x"}, {"a": 2, "b": "y"}]


def test_read_databricks_fake_transport(ray_start_regular):
    def transport(url, body, headers):
        assert headers["Authorization"] == "Bearer tok"
        assert "/api/2.0/sql/statements" in url
        return json.dumps(
            {
                "status": {"state": "SUCCEEDED"},
                "manifest": {"schema": {"columns": [{"name": "id"}, {"name": "v"}]}},
                "result": {"data_array": [[1, "a"], [2, "b"]]},
            }
        ).encode()

    out = rdata.read_databricks_tables(
        host="https://dbx", token="tok", warehouse_id="w1",
        query="SELECT * FROM t", transport=transport,
    ).take_all()
    assert out == [{"id": 1, "v": "a"}, {"id": 2, "v": "b"}]


def test_read_snowflake_dbapi_factory(ray_start_regular, tmp_path):
    import sqlite3

    db = str(tmp_path / "sf.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (id INTEGER, name TEXT)")
    conn.executemany("INSERT INTO t VALUES (?, ?)", [(i, f"n{i}") for i in range(6)])
    conn.commit()
    conn.close()
    out = rdata.read_snowflake(
        "SELECT id, name FROM t",
        connection_factory=lambda: sqlite3.connect(db),
    ).take_all()
    assert sorted(r["id"] for r in out) == list(range(6))


def test_gated_sources_error_clearly(ray_start_regular):
    for fn, args, kwargs in [
        (rdata.read_lance, ("/nope",), {}),
        (rdata.read_iceberg, ("db.t",), {}),
        (rdata.read_hudi, ("/nope",), {}),
        (rdata.read_snowflake, ("q",), {"connection_parameters": {"user": "u"}}),
    ]:
        with pytest.raises(ImportError) as e:
            fn(*args, **kwargs)
        assert "not installed" in str(e.value)


def test_read_parquet_bulk_alias(ray_start_regular, tmp_path):
    import pyarrow as pa
    from pyarrow import parquet as pq

    for i in range(4):
        pq.write_table(pa.table({"v": [i]}), str(tmp_path / f"f{i}.parquet"))
    out = sorted(r["v"] for r in rdata.read_parquet_bulk(str(tmp_path)).take_all())
    assert out == [0, 1, 2, 3]
