"""Datasource breadth: webdataset shards, gated Mongo/BigQuery, ray:// client.

Reference counterparts: ``python/ray/data/datasource/webdataset_datasource.py``,
``mongo_datasource.py``, ``bigquery_datasource.py``; ``ray://`` client mode
(``python/ray/util/client/``).
"""

import json
import tarfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


def _make_shard(path, n=4):
    with tarfile.open(path, "w") as tf:
        for i in range(n):
            for ext, payload in (
                ("txt", f"caption {i}".encode()),
                ("cls", str(i % 2).encode()),
                ("json", json.dumps({"idx": i}).encode()),
            ):
                import io

                info = tarfile.TarInfo(name=f"sample{i:04d}.{ext}")
                info.size = len(payload)
                tf.addfile(info, io.BytesIO(payload))


class TestWebDataset:
    def test_read_samples(self, ray_start_regular, tmp_path):
        shard = str(tmp_path / "data-0000.tar")
        _make_shard(shard, n=4)
        ds = rdata.read_webdataset(shard)
        rows = ds.take_all()
        assert len(rows) == 4
        assert rows[0]["txt"] == "caption 0"
        assert rows[0]["cls"] in (0, 1)
        assert rows[1]["json"]["idx"] == 1
        assert rows[2]["__key__"] == "sample0002"

    def test_multiple_shards_parallel(self, ray_start_regular, tmp_path):
        for i in range(3):
            _make_shard(str(tmp_path / f"data-{i:04d}.tar"), n=2)
        ds = rdata.read_webdataset(str(tmp_path / "data-*.tar"), parallelism=3)
        assert ds.count() == 6

    def test_no_decode(self, ray_start_regular, tmp_path):
        shard = str(tmp_path / "raw.tar")
        _make_shard(shard, n=1)
        rows = rdata.read_webdataset(shard, decode=False).take_all()
        assert rows[0]["txt"] == b"caption 0"


class TestGatedSources:
    def test_mongo_requires_pymongo(self):
        pytest.importorskip("ray_tpu")
        try:
            import pymongo  # noqa: F401

            pytest.skip("pymongo installed; gating not exercised")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="pymongo"):
            rdata.read_mongo("mongodb://x", "db", "coll")

    def test_bigquery_requires_client(self):
        try:
            from google.cloud import bigquery  # noqa: F401

            pytest.skip("bigquery installed; gating not exercised")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="bigquery"):
            rdata.read_bigquery("proj", query="select 1")


class TestRayClientScheme:
    def test_ray_scheme_attaches_over_tcp(self):
        """ray://host:port behaves as client mode against a live head."""
        import os
        import subprocess
        import sys

        # both sides must share the cluster secret (resolve_authkey)
        key = os.urandom(16).hex()
        env = dict(os.environ, RAY_TPU_AUTHKEY=key)
        # head in a separate process serving TCP
        script = (
            "import ray_tpu, time;"
            "info = ray_tpu.init(num_cpus=2);"
            "from ray_tpu._private.runtime import get_ctx;"
            "head = get_ctx().head;"
            "h, p = head.listen_tcp('127.0.0.1', 0);"
            "print(f'ADDR {h}:{p}', flush=True);"
            "time.sleep(60)"
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", script], stdout=subprocess.PIPE, text=True, env=env
        )
        os.environ["RAY_TPU_AUTHKEY"] = key
        try:
            line = proc.stdout.readline()
            assert line.startswith("ADDR"), line
            addr = line.split()[1]
            ray_tpu.init(address=f"ray://{addr}")
            try:

                @ray_tpu.remote
                def f(x):
                    return x * 7

                assert ray_tpu.get(f.remote(6), timeout=60) == 42
            finally:
                ray_tpu.shutdown()
        finally:
            os.environ.pop("RAY_TPU_AUTHKEY", None)
            proc.terminate()
            proc.wait(timeout=10)
