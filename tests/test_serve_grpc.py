"""Serve gRPC ingress (reference: ``serve/_private/proxy.py:542`` gRPCProxy
+ ``tests/test_grpc.py`` themes — generic-service variant, no codegen)."""

import pickle

import pytest

pytest.importorskip("grpc")

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve._private.grpc_proxy import SERVICE, grpc_channel_call


@pytest.fixture
def serve_shutdown():
    yield
    serve.shutdown()


def test_grpc_unary_and_routing(ray_start_regular, serve_shutdown):
    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return {"doubled": x * 2}

    @serve.deployment
    class Echo:
        def __call__(self, x):
            return x

    serve.run(Doubler.bind(), name="double", grpc=True)
    handle = serve.run(Echo.bind(), name="echo", grpc=True)
    assert handle is not None
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    port = ray_tpu.get(controller.get_grpc_proxy_port.remote(), timeout=30)
    addr = f"127.0.0.1:{port}"

    # pickle payloads route by application metadata
    assert grpc_channel_call(addr, "double", 21) == {"doubled": 42}
    assert grpc_channel_call(addr, "echo", [1, 2]) == [1, 2]

    # raw (non-pickle) bytes pass through untouched
    assert grpc_channel_call(addr, "echo", b"\x00raw") == b"\x00raw"


def test_grpc_errors_surface_as_status(ray_start_regular, serve_shutdown):
    import grpc

    @serve.deployment
    class Boom:
        def __call__(self, x):
            raise ValueError("kapow")

    serve.run(Boom.bind(), name="boom", grpc=True)
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    port = ray_tpu.get(controller.get_grpc_proxy_port.remote(), timeout=30)
    addr = f"127.0.0.1:{port}"

    with pytest.raises(grpc.RpcError) as e:
        grpc_channel_call(addr, "boom", 1)
    assert e.value.code() == grpc.StatusCode.INTERNAL
    assert "kapow" in e.value.details()

    with pytest.raises(grpc.RpcError) as e:
        grpc_channel_call(addr, "no-such-app", 1)
    assert e.value.code() == grpc.StatusCode.NOT_FOUND

    # missing application metadata
    with grpc.insecure_channel(addr) as ch:
        fn = ch.unary_unary(f"/{SERVICE}/Predict")
        with pytest.raises(grpc.RpcError) as e:
            fn(pickle.dumps(1), timeout=10)
        assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_grpc_streaming(ray_start_regular, serve_shutdown):
    @serve.deployment
    class Counter:
        def __call__(self, n):
            for i in range(n):
                yield {"i": i}

    serve.run(Counter.bind(), name="count", grpc=True)
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    port = ray_tpu.get(controller.get_grpc_proxy_port.remote(), timeout=30)
    items = grpc_channel_call(f"127.0.0.1:{port}", "count", 4, stream=True)
    assert items == [{"i": i} for i in range(4)]
