"""Serve gRPC ingress (reference: ``serve/_private/proxy.py:542`` gRPCProxy
+ ``tests/test_grpc.py`` themes — generic-service variant, no codegen).

Payload contract (VERDICT r4 #6): raw-bytes passthrough by DEFAULT;
pickle/json are per-deployment opt-ins (``grpc_codec=``). A non-Python
client sending pickle-shaped bytes must receive them verbatim unless the
deployment opted in — unpickling untrusted ingress is an RCE surface.
"""

import json
import pickle

import pytest

pytest.importorskip("grpc")

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve._private.grpc_proxy import SERVICE, grpc_channel_call


@pytest.fixture
def serve_shutdown():
    yield
    serve.shutdown()


def _grpc_addr():
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    port = ray_tpu.get(controller.get_grpc_proxy_port.remote(), timeout=30)
    return f"127.0.0.1:{port}"


def test_grpc_default_is_verbatim_bytes(ray_start_regular, serve_shutdown):
    """Pickle-SHAPED bytes from a non-Python client come back verbatim:
    the proxy must not probe-unpickle them."""
    seen = []

    @serve.deployment
    class Echo:
        def __call__(self, x):
            # the deployment sees raw bytes, exactly as sent
            return x

    serve.run(Echo.bind(), name="echo", grpc=True)
    addr = _grpc_addr()

    pickled = pickle.dumps({"cmd": "rm -rf"})  # a valid pickle on the wire
    out = grpc_channel_call(addr, "echo", pickled)  # default bytes codec
    assert out == pickled  # verbatim — NOT the unpickled dict

    assert grpc_channel_call(addr, "echo", b"\x00raw") == b"\x00raw"
    # str responses are utf-8 bytes on the wire
    assert grpc_channel_call(addr, "echo", "text") == b"text"


def test_grpc_pickle_codec_opt_in(ray_start_regular, serve_shutdown):
    @serve.deployment(grpc_codec="pickle")
    class Doubler:
        def __call__(self, x):
            return {"doubled": x * 2}

    serve.run(Doubler.bind(), name="double", grpc=True)
    addr = _grpc_addr()
    assert grpc_channel_call(addr, "double", 21, codec="pickle") == {"doubled": 42}

    # malformed pickle to an opted-in app is the client's error
    import grpc

    with grpc.insecure_channel(addr) as ch:
        fn = ch.unary_unary(f"/{SERVICE}/Predict")
        with pytest.raises(grpc.RpcError) as e:
            fn(b"\x00not-a-pickle", metadata=(("application", "double"),), timeout=10)
        assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_grpc_json_codec(ray_start_regular, serve_shutdown):
    @serve.deployment(grpc_codec="json")
    class Sum:
        def __call__(self, req):
            return {"sum": sum(req["values"])}

    serve.run(Sum.bind(), name="sum", grpc=True)
    addr = _grpc_addr()
    assert grpc_channel_call(addr, "sum", {"values": [1, 2, 3]}, codec="json") == {
        "sum": 6
    }

    # wire format really is JSON (interop: any language can call this)
    import grpc

    with grpc.insecure_channel(addr) as ch:
        fn = ch.unary_unary(f"/{SERVICE}/Predict")
        raw = fn(
            json.dumps({"values": [4, 5]}).encode(),
            metadata=(("application", "sum"),),
            timeout=10,
        )
        assert json.loads(raw.decode()) == {"sum": 9}
        with pytest.raises(grpc.RpcError) as e:
            fn(b"{nope", metadata=(("application", "sum"),), timeout=10)
        assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_grpc_bytes_codec_rejects_nonbytes_response(ray_start_regular, serve_shutdown):
    import grpc

    @serve.deployment  # default bytes codec, but returns a dict
    class Bad:
        def __call__(self, x):
            return {"oops": 1}

    serve.run(Bad.bind(), name="bad", grpc=True)
    with pytest.raises(grpc.RpcError) as e:
        grpc_channel_call(_grpc_addr(), "bad", b"x")
    assert e.value.code() == grpc.StatusCode.INTERNAL
    assert "grpc_codec" in e.value.details()


def test_grpc_errors_surface_as_status(ray_start_regular, serve_shutdown):
    import grpc

    @serve.deployment(grpc_codec="pickle")
    class Boom:
        def __call__(self, x):
            raise ValueError("kapow")

    serve.run(Boom.bind(), name="boom", grpc=True)
    addr = _grpc_addr()

    with pytest.raises(grpc.RpcError) as e:
        grpc_channel_call(addr, "boom", 1, codec="pickle")
    assert e.value.code() == grpc.StatusCode.INTERNAL
    assert "kapow" in e.value.details()

    with pytest.raises(grpc.RpcError) as e:
        grpc_channel_call(addr, "no-such-app", b"1")
    assert e.value.code() == grpc.StatusCode.NOT_FOUND

    # missing application metadata
    with grpc.insecure_channel(addr) as ch:
        fn = ch.unary_unary(f"/{SERVICE}/Predict")
        with pytest.raises(grpc.RpcError) as e:
            fn(b"1", timeout=10)
        assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_grpc_streaming(ray_start_regular, serve_shutdown):
    @serve.deployment(grpc_codec="pickle")
    class Counter:
        def __call__(self, n):
            for i in range(n):
                yield {"i": i}

    serve.run(Counter.bind(), name="count", grpc=True)
    items = grpc_channel_call(
        _grpc_addr(), "count", 4, stream=True, codec="pickle"
    )
    assert items == [{"i": i} for i in range(4)]
