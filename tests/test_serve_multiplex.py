"""Serve model multiplexing (reference: ``serve/multiplex.py`` +
``tests/test_multiplex.py`` themes: LRU model cache, per-model routing
stickiness, get_multiplexed_model_id)."""

import threading

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_instance():
    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_multiplexed_lru_and_context(serve_instance):
    @serve.deployment(num_replicas=1, max_ongoing_requests=8)
    class Multi:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id):
            self.loads.append(model_id)
            return lambda x: f"{model_id}:{x * 2}"

        def __call__(self, x):
            mid = serve.get_multiplexed_model_id()
            return self.get_model(mid)(x)

        def load_log(self):
            return list(self.loads)

    h = serve.run(Multi.bind(), name="mx")
    assert h.options(multiplexed_model_id="a").remote(3).result(timeout=30) == "a:6"
    assert h.options(multiplexed_model_id="b").remote(1).result(timeout=30) == "b:2"
    # cached: repeated model ids don't reload
    for _ in range(3):
        assert h.options(multiplexed_model_id="a").remote(1).result(timeout=30) == "a:2"
    assert h.load_log.remote().result(timeout=30) == ["a", "b"]
    # LRU capacity 2: a third model evicts the least-recently-used ("b")
    h.options(multiplexed_model_id="c").remote(0).result(timeout=30)
    h.options(multiplexed_model_id="b").remote(0).result(timeout=30)  # reload
    assert h.load_log.remote().result(timeout=30) == ["a", "b", "c", "b"]


def test_multiplexed_routing_is_sticky_per_model(serve_instance):
    @serve.deployment(num_replicas=2, max_ongoing_requests=8)
    class Who:
        def __init__(self):
            import os

            self.pid = os.getpid()
            self.loaded = []

        @serve.multiplexed(max_num_models_per_replica=8)
        def get_model(self, model_id):
            self.loaded.append(model_id)
            return model_id

        def __call__(self, _):
            mid = serve.get_multiplexed_model_id()
            self.get_model(mid)
            return (mid, self.pid)

    h = serve.run(Who.bind(), name="sticky")
    # Rendezvous hashing keys on replica ACTOR IDS (random per run), so the
    # model->replica assignment is an independent coin flip per model id:
    # with M models over 2 replicas, P(all land on one replica) = 2^(1-M).
    # The original M=4 flaked at that 12.5% rate in a full-suite run;
    # M=12 (~0.05%) keeps the both-replicas-used assertion meaningful
    # without betting the suite on hash luck.
    mids = tuple(f"m{i}" for i in range(12))
    seen = {}
    for _ in range(3):
        for mid in mids:
            got_mid, pid = h.options(multiplexed_model_id=mid).remote(0).result(timeout=30)
            assert got_mid == mid
            seen.setdefault(mid, set()).add(pid)
    # every model id consistently routed to ONE replica
    assert all(len(pids) == 1 for pids in seen.values()), seen
    # and with 12 models over 2 replicas, both replicas serve something
    assert len({next(iter(p)) for p in seen.values()}) == 2


def test_plain_requests_unaffected(serve_instance):
    @serve.deployment
    class Plain:
        def __call__(self, x):
            return (serve.get_multiplexed_model_id(), x + 1)

    h = serve.run(Plain.bind(), name="plain")
    assert h.remote(1).result(timeout=30) == ("", 2)
