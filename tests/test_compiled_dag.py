"""Compiled DAGs over reusable shm channels.

Reference: ``python/ray/dag/compiled_dag_node.py:141`` (accelerated DAGs),
``python/ray/experimental/channel.py:49`` (mutable channels).
"""

import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.experimental.channel import Channel, ChannelClosed


class TestChannel:
    def test_same_process_roundtrip(self):
        ch = Channel(1 << 16)
        ch.write({"a": 1})
        assert ch.read() == {"a": 1}
        ch.destroy()

    def test_rendezvous_blocks_second_write(self):
        ch = Channel(1 << 16)
        ch.write(1)
        with pytest.raises(TimeoutError):
            ch.write(2, timeout=0.2)  # first value unread
        assert ch.read() == 1
        ch.write(2, timeout=1.0)
        assert ch.read() == 2
        ch.destroy()

    def test_capacity_enforced(self):
        ch = Channel(128)
        with pytest.raises(ValueError, match="capacity"):
            ch.write(b"x" * 1024)
        ch.destroy()

    def test_close_wakes_reader(self):
        import threading

        ch = Channel(1 << 16)
        got = []

        def reader():
            try:
                ch.read(timeout=10)
            except ChannelClosed:
                got.append("closed")

        t = threading.Thread(target=reader)
        t.start()
        time.sleep(0.2)
        ch.close()
        t.join(timeout=10)
        assert got == ["closed"]
        ch.destroy()

    def test_cross_process_channel(self, ray_start_regular):
        ch = Channel(1 << 16)

        @ray_tpu.remote
        def produce(c):
            for i in range(5):
                c.write(i * 10)
            return "done"

        ref = produce.remote(ch)
        assert [ch.read(timeout=30) for _ in range(5)] == [0, 10, 20, 30, 40]
        assert ray_tpu.get(ref, timeout=30) == "done"
        ch.destroy()


@pytest.fixture
def two_stage_dag(ray_start_regular):
    @ray_tpu.remote
    class Doubler:
        def double(self, x):
            return 2 * x

    @ray_tpu.remote
    class Adder:
        def __init__(self):
            self.calls = 0

        def add_one(self, x):
            self.calls += 1
            return x + 1

        def ncalls(self):
            return self.calls

    d, a = Doubler.remote(), Adder.remote()
    with InputNode() as inp:
        dag = a.add_one.bind(d.double.bind(inp))
    compiled = dag.experimental_compile()
    yield compiled, d, a
    compiled.teardown()


class TestCompiledDAG:
    def test_pipeline_executes(self, two_stage_dag):
        compiled, _, _ = two_stage_dag
        assert compiled.execute(5).get() == 11
        assert compiled.execute(0).get() == 1

    def test_no_task_submissions_after_warmup(self, two_stage_dag):
        """The accelerated property: repeated executes run over channels,
        not the scheduler — the head sees no new tasks."""
        compiled, _, _ = two_stage_dag
        compiled.execute(1).get()
        from ray_tpu._private.runtime import get_ctx

        head = get_ctx().head
        with head.lock:
            tasks_before = len(head.tasks) + len(head.task_events)
        for i in range(20):
            assert compiled.execute(i).get() == 2 * i + 1
        with head.lock:
            tasks_after = len(head.tasks) + len(head.task_events)
        assert tasks_after == tasks_before

    def test_throughput_beats_remote_calls(self, two_stage_dag):
        """Channel round-trips should be much faster than two chained
        task submissions per item."""
        compiled, d, a = two_stage_dag
        compiled.execute(1).get()  # warm
        n = 50
        t0 = time.perf_counter()
        for i in range(n):
            compiled.execute(i).get()
        dag_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        # the same computation via plain actor calls requires the dag loops'
        # actors; use fresh refs through the scheduler path
        for i in range(10):
            ray_tpu.get(ray_tpu.put(i))  # cheapest scheduler round-trip proxy
        rpc_dt = (time.perf_counter() - t0) / 10
        assert dag_dt / n < max(rpc_dt * 4, 0.05), (dag_dt / n, rpc_dt)

    def test_errors_propagate_and_dag_survives(self, ray_start_regular):
        @ray_tpu.remote
        class Fragile:
            def work(self, x):
                if x < 0:
                    raise ValueError("negative!")
                return x * 3

        f = Fragile.remote()
        with InputNode() as inp:
            dag = f.work.bind(inp)
        compiled = dag.experimental_compile()
        try:
            assert compiled.execute(2).get() == 6
            with pytest.raises(ValueError, match="negative"):
                compiled.execute(-1).get()
            assert compiled.execute(3).get() == 9  # loop survived the error
        finally:
            compiled.teardown()

    def test_multi_output(self, ray_start_regular):
        @ray_tpu.remote
        class Sq:
            def sq(self, x):
                return x * x

        @ray_tpu.remote
        class Neg:
            def neg(self, x):
                return -x

        s, n = Sq.remote(), Neg.remote()
        with InputNode() as inp:
            dag = MultiOutputNode([s.sq.bind(inp), n.neg.bind(inp)])
        compiled = dag.experimental_compile()
        try:
            assert compiled.execute(4).get(timeout=30) == [16, -4]
        finally:
            compiled.teardown()

    def test_async_actor_in_compiled_dag(self, ray_start_regular):
        """An actor with any async method runs its task loop on the asyncio
        engine; the compiled-DAG exec loop must still resolve and must not
        block the event loop (regression: _arun used getattr, so
        __dag_exec__ raised AttributeError into the void and execute().get()
        surfaced only as a channel timeout)."""

        @ray_tpu.remote
        class A:
            async def poke(self):
                return "alive"

            def double(self, x):
                return 2 * x

        a = A.remote()
        with InputNode() as inp:
            dag = a.double.bind(inp)
        compiled = dag.experimental_compile()
        try:
            assert compiled.execute(21).get(timeout=30) == 42
            # other (async) methods stay serviceable while the DAG loop runs
            assert ray_tpu.get(a.poke.remote(), timeout=30) == "alive"
            assert compiled.execute(5).get(timeout=30) == 10
        finally:
            compiled.teardown()

    def test_actor_usable_after_teardown(self, ray_start_regular):
        @ray_tpu.remote
        class W:
            def f(self, x):
                return x + 100

        w = W.remote()
        with InputNode() as inp:
            dag = w.f.bind(inp)
        compiled = dag.experimental_compile()
        assert compiled.execute(1).get() == 101
        compiled.teardown()
        # the exec loop released the actor's dispatch queue
        assert ray_tpu.get(w.f.remote(5), timeout=30) == 105
