"""Zero-copy shm object plane + locality-aware scheduling (ISSUE 18).

Producers above ``core_shm_inline_threshold`` write straight into shared
memory and ship only the locator over the control socket; same-host
consumers map the bytes back out (pin-refcounted), and the scheduler moves
tasks to the node already holding their argument bytes. Reference: the
plasma object store + locality-aware leasing (Ray §4,
``scheduling/policy/hybrid_scheduling_policy.cc`` locality term).
"""

import gc
import os
import pickle
import signal
import tempfile
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions as rex
from ray_tpu._private import serialization as ser
from ray_tpu._private import shm_store
from ray_tpu._private.config import GLOBAL_CONFIG, resolve_authkey
from ray_tpu._private.head import Head
from ray_tpu._private.node_agent import NodeAgent
from ray_tpu._private.runtime import get_ctx

THRESH = GLOBAL_CONFIG.core_shm_inline_threshold

#: sizes straddling every storage-band boundary: inline, the shm threshold
#: edge, mid-band (the (threshold, 100KB] band that used to ride the socket
#: twice), the old 100KB cutoff edge, and a large arena object
BOUNDARY_SIZES = (
    100,
    THRESH - 64,
    THRESH + 64,
    64 * 1024,
    100 * 1024 + 64,
    1024 * 1024,
)


def _blob(n: int) -> bytes:
    # non-constant content so a layout/offset bug can't hide behind
    # compressible or repeated bytes
    return bytes(bytearray((i * 31 + n) % 251 for i in range(n)))


# ---------------------------------------------------------------------------
# get() == put() identity across the size boundaries, per context kind
# ---------------------------------------------------------------------------


def test_identity_boundaries_driver(ray_start_regular):
    head = get_ctx().head
    for n in BOUNDARY_SIZES:
        data = _blob(n)
        ref = ray_tpu.put(data)
        ent = head.objects[ref.binary()]
        if n > THRESH and head.arena_name is not None:
            assert ent.shm is not None, f"{n}B put should be shm-backed"
        else:
            assert ent.small is not None, f"{n}B put should stay inline"
        assert ray_tpu.get(ref, timeout=30) == data


def test_identity_boundaries_worker(ray_start_regular):
    sizes = list(BOUNDARY_SIZES)

    @ray_tpu.remote
    def round_trip(n):
        # worker-context put + get: the worker mints the locator itself
        data = bytes(bytearray((i * 31 + n) % 251 for i in range(n)))
        ref = ray_tpu.put(data)
        return ray_tpu.get(ref, timeout=30) == data

    assert all(ray_tpu.get([round_trip.remote(n) for n in sizes], timeout=120))

    @ray_tpu.remote
    def produce(n):
        return bytes(bytearray((i * 31 + n) % 251 for i in range(n)))

    # worker-produced results resolve identically from the driver
    outs = ray_tpu.get([produce.remote(n) for n in sizes], timeout=120)
    assert outs == [_blob(n) for n in sizes]


def test_identity_boundaries_ray_client():
    """ray:// context: remote driver without arena access ships inline and
    the head re-lays — identity must hold across the same boundaries."""
    key = os.urandom(16).hex()
    os.environ["RAY_TPU_AUTHKEY"] = key
    session = tempfile.mkdtemp(prefix="ray_tpu_zcp_")
    head = Head(os.path.join(session, "head.sock"), authkey=resolve_authkey())
    head.start()
    host, port = head.listen_tcp("127.0.0.1", 0)
    head.add_node({"CPU": 2.0})
    try:
        ray_tpu.init(address=f"ray://{host}:{port}")
        for n in BOUNDARY_SIZES:
            data = _blob(n)
            assert ray_tpu.get(ray_tpu.put(data), timeout=30) == data
    finally:
        os.environ.pop("RAY_TPU_AUTHKEY", None)
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        head.shutdown()


def test_identity_across_spill_boundary():
    """Objects pushed over the spill watermark restore to their put() value."""
    ray_tpu.init(
        num_cpus=2,
        _system_config={"object_spilling_threshold_bytes": 4 * 1024 * 1024},
    )
    try:
        blobs = [_blob(1024 * 1024 + i) for i in range(8)]
        refs = [ray_tpu.put(b) for b in blobs]
        for ref, b in zip(refs, blobs):
            assert ray_tpu.get(ref, timeout=60) == b
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# pin refcounting: two consumers over one locator, freed under them
# ---------------------------------------------------------------------------


def test_two_consumers_pin_one_locator(ray_start_regular):
    head = get_ctx().head
    if head.arena_name is None:
        pytest.skip("native arena unavailable")
    arena = shm_store.attach_arena(head.arena_name)
    base = arena.n_objects

    arr = np.arange(32 * 1024, dtype=np.int64)  # 256KB, arena-resident
    ref = ray_tpu.put(arr)
    ref_id = ref.binary()
    loc = head.objects[ref_id].shm
    assert loc is not None and loc.offset is not None

    # two independent consumers attach the same block; each read pins it
    r1, r2 = shm_store.ShmReader(loc), shm_store.ShmReader(loc)
    v1, v2 = r1.read(), r2.read()
    assert (v1 == arr).all() and (v2 == arr).all()

    # free the only ref while both consumers hold live views: the arena
    # free must defer to the last unpin, never unmap under a reader
    del ref
    gc.collect()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        with head.lock:
            if ref_id not in head.objects:
                break
        time.sleep(0.05)
    assert (v1 == arr).all() and (v2 == arr).all()  # reads survive the free

    # dropping one consumer keeps the block alive for the other
    del v1, r1
    gc.collect()
    assert (v2 == arr).all()

    # last consumer gone -> the deferred free lands, no arena bytes leak
    del v2, r2
    gc.collect()
    deadline = time.monotonic() + 20
    while arena.n_objects != base and time.monotonic() < deadline:
        gc.collect()
        time.sleep(0.05)
    assert arena.n_objects == base


# ---------------------------------------------------------------------------
# byte accounting: same-node get ships locators, never payload bytes
# ---------------------------------------------------------------------------


def test_same_node_get_zero_payload_copies(ray_start_regular, monkeypatch):
    """The 64KB band rides the control socket as LOCATORS in both
    directions: every head->worker message and every worker->head
    completion payload stays far below the object size (byte accounting,
    not vibes), and the head serves zero inline bytes."""
    head = get_ctx().head
    if head.arena_name is None:
        pytest.skip("native arena unavailable")
    N = 64 * 1024

    sent_sizes = []  # every head-side socket write (run_task, resp, ...)
    real_send = ser.conn_send

    def spy_send(conn, msg):
        sent_sizes.append(len(pickle.dumps(msg)))
        return real_send(conn, msg)

    monkeypatch.setattr(ser, "conn_send", spy_send)

    done_sizes = []  # worker->head completion payloads (just deserialized
    real_done = head._on_task_done  # off the socket: same bytes that crossed)
    real_batch = head._on_task_done_batch

    def spy_done(wh, payload):
        done_sizes.append(len(pickle.dumps(payload)))
        return real_done(wh, payload)

    def spy_batch(wh, payloads):
        done_sizes.extend(len(pickle.dumps(p)) for p in payloads)
        return real_batch(wh, payloads)

    head._on_task_done = spy_done
    head._on_task_done_batch = spy_batch

    @ray_tpu.remote
    def produce():
        return bytes(N)

    @ray_tpu.remote
    def consume(b):
        return len(b)

    base_inline = head.inline_bytes_served
    ref = produce.remote()
    assert ray_tpu.get(ref, timeout=60) == bytes(N)  # driver-side read
    assert ray_tpu.get(consume.remote(ref), timeout=60) == N  # worker read

    assert done_sizes, "no completion payloads observed"
    assert max(done_sizes) < N // 4, (
        f"a completion payload carried object bytes: {max(done_sizes)}B"
    )
    big_sends = [s for s in sent_sizes if s >= N]
    assert not big_sends, f"payload-sized socket writes: {big_sends}"
    assert head.inline_bytes_served == base_inline


# ---------------------------------------------------------------------------
# locality-aware scheduling
# ---------------------------------------------------------------------------


def test_tasks_follow_their_data(ray_start_regular):
    head = get_ctx().head
    data_node = head.add_node({"CPU": 2.0, "prod": 4.0})

    @ray_tpu.remote(resources={"prod": 1.0})
    def produce():
        return bytes(256 * 1024)

    @ray_tpu.remote(num_cpus=1)
    def where(b):
        return ray_tpu.get_runtime_context().get_node_id()

    ref = produce.remote()
    ray_tpu.wait([ref], timeout=60)
    with head.lock:
        assert head.objects[ref.binary()].shm.node == data_node.binary()

    # unconstrained single-arg consumers follow the bytes (>=90% is the
    # acceptance bar; sequential placement with capacity free should hit it)
    placements = [ray_tpu.get(where.remote(ref), timeout=60) for _ in range(12)]
    hits = sum(1 for p in placements if p == data_node.hex())
    assert hits >= int(0.9 * len(placements)), placements
    assert head._loc_total >= 12 and head._loc_hits >= hits


def test_locality_yields_when_data_node_full(ray_start_regular):
    """A byte-holding node with no capacity must not wedge placement: the
    task falls through to the hybrid policy and runs elsewhere."""
    head = get_ctx().head
    tiny = head.add_node({"CPU": 1.0, "prod": 1.0})

    @ray_tpu.remote(resources={"prod": 1.0}, num_cpus=0)
    def produce():
        return bytes(64 * 1024)

    @ray_tpu.remote(resources={"prod": 1.0}, num_cpus=1)
    def camp(sec):
        time.sleep(sec)
        return True

    @ray_tpu.remote(num_cpus=1)
    def consume(b):
        return len(b)

    ref = produce.remote()
    ray_tpu.wait([ref], timeout=60)
    camper = camp.remote(3.0)  # occupies tiny's only CPU
    time.sleep(0.3)
    # must not wait out the camper: the fallback node serves it promptly
    assert ray_tpu.get(consume.remote(ref), timeout=60) == 64 * 1024
    assert ray_tpu.get(camper, timeout=60)


def test_no_arg_tasks_unaffected(ray_start_regular):
    """The no-arg hot path stays locality-free (tasks_async regression
    guard): placements without ref args never touch the locality counters."""
    head = get_ctx().head

    @ray_tpu.remote
    def f():
        return 1

    base = head._loc_total
    assert sum(ray_tpu.get([f.remote() for _ in range(64)], timeout=60)) == 64
    assert head._loc_total == base


# ---------------------------------------------------------------------------
# chaos: producer death / owning-node death
# ---------------------------------------------------------------------------


@pytest.fixture
def p2p_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_FORCE_DATA_PLANE", "1")
    authkey = resolve_authkey()
    session = tempfile.mkdtemp(prefix="ray_tpu_zcp_chaos_")
    head = Head(os.path.join(session, "head.sock"), authkey=authkey)
    head.start()
    host, port = head.listen_tcp("127.0.0.1", 0)
    head.add_node({"CPU": 0.0})
    addr = f"{host}:{port}"
    a = NodeAgent(addr, authkey, resources={"CPU": 2.0, "nodeA": 10.0}).start()
    yield {"head": head, "a": a, "address": addr}
    try:
        ray_tpu.shutdown()
    except Exception:
        pass
    a.shutdown()
    head.shutdown()


def test_sigkill_producer_then_node_death_reaps_locators(p2p_cluster):
    """SIGKILL the worker that wrote live arena blocks: the blocks belong
    to the node's arena, not the worker, so readers keep working. Then
    kill the owning NODE: readers of the now-lost (lineage-free) object
    get a retriable ObjectLostError, the directory reaps the node's
    locators, and no arena bytes leak into the head-side ledger."""
    ray_tpu.init(address=p2p_cluster["address"])
    head = p2p_cluster["head"]
    agent = p2p_cluster["a"]

    @ray_tpu.remote(resources={"nodeA": 1.0})
    def produce():
        # ray.put from the worker: a lineage-FREE arena object owned by
        # nodeA, outliving this worker process
        ref = ray_tpu.put(np.full(64 * 1024, 9, dtype=np.int64))
        return os.getpid(), ref

    pid, ref = ray_tpu.get(produce.remote(), timeout=60)
    with head.lock:
        loc = head.objects[ref.binary()].shm
    assert loc is not None and loc.node == agent.node_id_bin

    os.kill(pid, signal.SIGKILL)  # the producer dies; its blocks must not
    time.sleep(0.5)  # (give the head time to notice the death)
    out = ray_tpu.get(ref, timeout=60)  # bytes survive in the node arena
    assert (out[::1024] == 9).all()

    base_head_bytes = head.shm_owner.bytes_used
    from ray_tpu._private.ids import NodeID

    head.remove_node(NodeID(agent.node_id_bin))
    with pytest.raises(rex.ObjectLostError):
        ray_tpu.get(ref, timeout=30)
    with head.lock:
        leaked = [
            oid.hex()
            for oid, e in head.objects.items()
            if e.shm is not None and e.shm.node == agent.node_id_bin
        ]
    assert not leaked, f"directory kept dead-node locators: {leaked}"
    # audit invariant: nothing from the dead node ever entered (or stayed
    # in) the head's own shm ledger
    assert head.shm_owner.bytes_used == base_head_bytes


# ---------------------------------------------------------------------------
# get_inline fallback honors the caller's timeout budget
# ---------------------------------------------------------------------------


def test_get_inline_fallback_honors_timeout_budget(ray_start_regular, monkeypatch):
    """When the data plane errors out, the head-mediated fallback must ask
    with the caller's REMAINING budget — the old timeout=0 poll declared
    loss on locators the head was still re-laying."""
    from ray_tpu._private import data_plane

    ctx = get_ctx()
    loc = shm_store.ShmLocation(
        "/nope", 8, [], 8, offset=None, node=b"\x01" * 16
    )

    monkeypatch.setattr(ctx, "_data_address_for", lambda node: ("127.0.0.1", 1))

    def boom(addr, key, payload):
        raise OSError("owner unreachable")

    monkeypatch.setattr(data_plane, "fetch", boom)

    seen = {}
    expect = ser.serialize("recovered").to_bytes()

    def fake_call(method, **kw):
        assert method == "get_inline"
        seen["timeout"] = kw.get("timeout")
        return [("inline", expect, False)]

    monkeypatch.setattr(ctx, "call", fake_call)

    deadline = time.monotonic() + 7.5
    ok, value = ctx._fetch_via_data_plane(b"o" * 16, loc, deadline)
    assert ok and value == "recovered"
    assert seen["timeout"] is not None and 6.0 < seen["timeout"] <= 7.5

    # no deadline (get(timeout=None)): the fallback may block like get does
    ok, _ = ctx._fetch_via_data_plane(b"o" * 16, loc, None)
    assert ok and seen["timeout"] is None


# ---------------------------------------------------------------------------
# waterfall contract: locator-bearing replies keep all 7 legs
# ---------------------------------------------------------------------------


def test_waterfall_complete_for_locator_replies(ray_start_regular):
    from ray_tpu.util import tracing
    from ray_tpu.util import waterfall as wfl

    wfl.clear()

    @ray_tpu.remote
    def big(i):
        return bytes(64 * 1024)  # shm-threshold band: reply is a locator

    before = get_ctx().call("waterfall")["folded"]
    with tracing.trace_context() as rid:
        outs = ray_tpu.get([big.remote(i) for i in range(8)], timeout=120)
    assert all(len(o) == 64 * 1024 for o in outs)
    s = get_ctx().call("waterfall", recent=32)
    assert s["folded"] - before == 8
    assert s["incomplete"] == 0
    ours = [rec for rec in s["recent"] if rec.get("request_id") == rid]
    assert len(ours) == 8
    for rec in ours:
        stamps = rec["stamps"]
        assert len(stamps) == len(wfl.PHASES)  # reply_recv at head receipt
        assert stamps == sorted(stamps)
        assert all(v >= 0 for v in rec["legs"].values())


# ---------------------------------------------------------------------------
# pipelined (fire-and-forget) worker puts
# ---------------------------------------------------------------------------


def test_pipelined_put_failure_lands_on_the_ref(ray_start_regular):
    """``rpc_put`` never raises: a store failure is recorded ON the object
    id as an error locator, so a fire-and-forget putter's later ``get``
    raises instead of parking forever in the not-yet-arrived wait."""
    from ray_tpu._private.runtime import ObjectID

    head = get_ctx().head
    orig = head._normalize_locator

    def boom(loc):
        raise RuntimeError("store exploded")

    head._normalize_locator = boom
    try:
        oid = ObjectID.for_put().binary()
        # True: the delivery was APPLIED (as an error-store) — only ignored
        # replay duplicates return False
        assert head.rpc_put(oid, small=b"\x01", shm=None, take_ref=True) is True
    finally:
        head._normalize_locator = orig
    loc = head.get_locators([oid], 1.0)[0]
    assert loc[0] == "inline" and loc[2] is True
    err = ser.deserialize_value(ser.SerializedValue.from_bytes(loc[1]))
    assert isinstance(err, RuntimeError)


def test_pipelined_put_replay_is_idempotent(ray_start_regular):
    """A reconnecting client replays puts from un-acked windows — the head
    may have processed the original (only the ack was lost). Replay-flagged
    redelivery of an already-stored put must be ignored: no re-store, no
    take_ref double-count."""
    from ray_tpu._private.runtime import ObjectID

    head = get_ctx().head
    oid = ObjectID.for_put().binary()
    assert head.rpc_put(oid, small=b"\x05", shm=None, take_ref=True) is True
    with head.lock:
        rc0 = head.objects[oid].refcount
    # redelivery: dup detected, side effects NOT applied again
    assert head.rpc_put(oid, small=b"\x05", shm=None, take_ref=True, replay=True) is False
    with head.lock:
        assert head.objects[oid].refcount == rc0
    # a replay whose original never landed stores normally
    oid2 = ObjectID.for_put().binary()
    assert head.rpc_put(oid2, small=b"\x07", shm=None, take_ref=True, replay=True) is True
    loc = head.get_locators([oid2], 1.0)[0]
    assert loc[0] == "inline" and loc[1] == b"\x07"


def test_pipelined_put_then_immediate_use_as_arg(ray_start_regular):
    """A worker's fire-and-forget put followed by a nested submit that
    consumes the ref resolves in order: the head reads each connection's
    messages sequentially, so the put always lands before the submit."""

    @ray_tpu.remote
    def double(x):
        return x * 2

    @ray_tpu.remote
    def chain():
        ref = ray_tpu.put(np.arange(32 * 1024, dtype=np.int32))  # shm band
        return int(ray_tpu.get(double.remote(ref), timeout=30).sum())

    expect = int((np.arange(32 * 1024, dtype=np.int64) * 2).sum())
    assert ray_tpu.get(chain.remote(), timeout=60) == expect


# ---------------------------------------------------------------------------
# object-plane flight deck: poison forensics, ledger, leak audit (ISSUE 19)
# ---------------------------------------------------------------------------


def test_giveup_swept_put_leaves_poison_forensics(p2p_cluster):
    """A pipelined put caught in the give-up sweep (reconnect failed /
    context closing) must leave a forensic trail: a ``core.object.poison``
    event on this process's flight-recorder ring, a retriable error on
    the ref's get, and a ``_poisoned`` entry that lives exactly as long
    as the ref — dropping the last handle drops the entry."""
    from ray_tpu._private import events
    from ray_tpu._private.runtime import ObjectID, ObjectRef

    ray_tpu.init(address=p2p_cluster["address"])
    ctx = get_ctx()
    oid = ObjectID.for_put().binary()
    # a buffered fire-and-forget put that never reached any connection
    with ctx._submit_cv:
        ctx._submit_buf.append(("put", {
            "obj_id": oid, "small": b"\x01", "shm": None, "is_error": False,
            "take_ref": True, "return_ids": [oid],
        }))
    ctx._fail_submits(replay_puts=False)  # the give-up sweep

    evs = [
        e for e in events.snapshot()
        if e["type"] == "core.object.poison" and e.get("oid") == oid.hex()
    ]
    assert evs, "give-up sweep emitted no core.object.poison event"
    assert evs[-1]["reason"] == "conn-lost"

    ref = ObjectRef(oid, owned=True)
    # plain try/except, not pytest.raises: the raised error IS the
    # _poisoned entry, and excinfo would pin its traceback (whose frames
    # reference the ref) past the del below
    try:
        ray_tpu.get(ref, timeout=5)
    except rex.RayError:
        pass
    else:
        pytest.fail("get on a poisoned ref did not raise")
    assert oid in ctx._poisoned
    del ref
    gc.collect()
    assert oid not in ctx._poisoned, "ref drop must clear the poison entry"


def test_poisoned_ref_folds_into_ledger_until_drop(ray_start_regular):
    """The ledger shows a client-side poisoned ref as state ``poisoned``
    (worker/driver reports folded in) until the ref drops."""
    from ray_tpu._private.runtime import ObjectID

    ctx = get_ctx()
    oid = ObjectID.for_put().binary()
    ctx._poisoned[oid] = rex.RayError("submit window lost")
    try:
        led = ctx.call("object_ledger", timeout=0.0)
        mine = [p for p in led["poisoned"] if p["object_id"] == oid.hex()]
        assert mine and mine[0]["state"] == "poisoned"
        assert mine[0]["node"] == "head"
        assert led["summary"]["poisoned"] >= 1
    finally:
        ctx._poisoned.pop(oid, None)
    led = ctx.call("object_ledger", timeout=0.0)
    assert not [p for p in led["poisoned"] if p["object_id"] == oid.hex()]


def test_object_ledger_states_and_freed_tail(ray_start_regular):
    """Directory rows carry state/node/size/age; a freed object lands in
    the forensics tail with its lifetime and reason."""
    ctx = get_ctx()
    blob = np.ones(64 * 1024, np.uint8)  # shm band
    ref = ray_tpu.put(blob)
    small = ray_tpu.put(b"tiny")  # inline band
    led = ctx.call("object_ledger", timeout=0.0)
    by_id = {r["object_id"]: r for r in led["objects"]}
    row = by_id[ref.binary().hex()]
    assert row["state"] in ("arena", "segment")
    assert row["size"] >= blob.nbytes
    assert row["age_s"] >= 0.0 and row["seg"]
    assert by_id[small.binary().hex()]["state"] == "inline"
    assert led["summary"]["by_state"].get("inline", 0) >= 1
    assert "head" in led["nodes"]
    assert led["nodes"]["head"]["capacity"] > 0

    freed_hex = ref.binary().hex()
    del ref
    gc.collect()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        led = ctx.call("object_ledger", timeout=0.0)
        hits = [f for f in led["freed"] if f["object_id"] == freed_hex]
        if hits:
            assert hits[0]["reason"] == "refcount"
            assert hits[0]["size"] >= blob.nbytes
            break
        time.sleep(0.1)
    else:
        pytest.fail("freed object never reached the forensics tail")


def test_audit_clean_after_sigkill_chaos_then_detects_injected_orphan(
    p2p_cluster,
):
    """The acceptance invariant: after producer-SIGKILL chaos the audit
    reports ZERO leaks — every owner-registered byte has a live locator,
    every spill file a spilled entry — and a deliberately injected
    orphan (the test-only hook registers real bytes with no directory
    entry, exactly what a producer SIGKILLed after its put landed
    leaves) is detected with node + object provenance."""
    ray_tpu.init(address=p2p_cluster["address"])
    ctx = get_ctx()

    @ray_tpu.remote(resources={"nodeA": 1.0})
    def produce():
        ref = ray_tpu.put(np.full(32 * 1024, 7, dtype=np.int64))
        return os.getpid(), ref

    pid, ref = ray_tpu.get(produce.remote(), timeout=60)
    os.kill(pid, signal.SIGKILL)  # producer dies, its arena blocks live on
    time.sleep(0.5)
    out = ray_tpu.get(ref, timeout=60)
    assert (out[::1024] == 7).all()
    # head-side churn too: locators the head itself lays out and frees
    churn = [ray_tpu.put(np.ones(200 * 1024, np.uint8)) for _ in range(3)]
    for r in churn:
        assert ray_tpu.get(r, timeout=30).nbytes == 200 * 1024

    audit = ctx.call("object_audit", timeout=1.0)
    assert audit["findings"] == [], audit["findings"]
    assert audit["checked"]["objects"] >= 2

    inj = ctx.call("inject_orphan_for_tests", size=8192)
    audit = ctx.call("object_audit", timeout=1.0)
    orphans = [
        f for f in audit["findings"]
        if f["kind"] == "orphaned-bytes"
        and f["seg"] == inj["seg"] and f["offset"] == inj["offset"]
    ]
    assert orphans, f"injected orphan not reported: {audit['findings']}"
    assert orphans[0]["node"] == "head"
    assert orphans[0]["size"] == inj["size"]
