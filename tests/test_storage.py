"""Cloud-capable checkpoint storage (train/_storage.py) on pyarrow.fs.

Reference: ``python/ray/train/_internal/storage.py`` StorageContext +
``train/_checkpoint.py:56`` (Checkpoint = directory on a pyarrow filesystem,
``from_uri/to_uri`` cloud round-trip). Tests drive both the ``file://`` URI
path and an injected custom filesystem (SubTreeFileSystem = the local mock
for S3/GS), including restore-after-local-loss — the "head died, storage
survives" scenario SURVEY §7 checkpoint-restart elasticity requires.
"""

import json
import os

import numpy as np
import pytest

from ray_tpu.train._checkpoint import Checkpoint, load_pytree, save_pytree
from ray_tpu.train._checkpoint_manager import CheckpointManager
from ray_tpu.train._config import CheckpointConfig
from ray_tpu.train._storage import StorageContext, get_fs_and_path, is_uri


def _subtree_fs(tmp_path):
    from pyarrow import fs as pafs

    root = str(tmp_path / "bucket")
    os.makedirs(root, exist_ok=True)
    return pafs.SubTreeFileSystem(root, pafs.LocalFileSystem()), root


def test_get_fs_and_path_variants(tmp_path):
    from pyarrow import fs as pafs

    fs, p = get_fs_and_path(str(tmp_path))
    assert isinstance(fs, pafs.LocalFileSystem) and p == str(tmp_path)
    fs, p = get_fs_and_path(f"file://{tmp_path}")
    assert isinstance(fs, pafs.LocalFileSystem) and p == str(tmp_path)
    custom, _root = _subtree_fs(tmp_path)
    fs, p = get_fs_and_path("exp/a", storage_filesystem=custom)
    assert fs is custom and p == "exp/a"
    assert is_uri("s3://b/k") and not is_uri("/local/path")


def test_checkpoint_uri_roundtrip(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    (src / "model.bin").write_bytes(b"\x01\x02" * 100)
    (src / "sub").mkdir()
    (src / "sub" / "extra.json").write_text(json.dumps({"k": 1}))

    uri = f"file://{tmp_path}/remote/ckpt0"
    remote = Checkpoint.from_directory(str(src)).to_uri(uri)
    assert remote.path == uri

    back = Checkpoint.from_uri(uri)
    out = back.to_directory(str(tmp_path / "down"))
    assert (tmp_path / "down" / "model.bin").read_bytes() == b"\x01\x02" * 100
    assert json.loads((tmp_path / "down" / "sub" / "extra.json").read_text()) == {"k": 1}
    # metadata reads/writes go through the filesystem
    back.update_metadata({"step": 7})
    assert Checkpoint.from_uri(uri).get_metadata()["step"] == 7
    assert os.path.isdir(out)


def test_save_load_pytree_via_uri(tmp_path):
    tree = {"w": np.arange(6, dtype=np.float32).reshape(2, 3), "b": np.float32(1.5)}
    uri = f"file://{tmp_path}/store/pytree_ckpt"
    ckpt = save_pytree(tree, uri, step=3)
    assert ckpt.path == uri
    restored = load_pytree(Checkpoint.from_uri(uri))
    np.testing.assert_array_equal(restored["w"], tree["w"])
    assert float(restored["b"]) == 1.5


def test_manager_commits_to_storage_and_prunes(tmp_path):
    custom, root = _subtree_fs(tmp_path)
    storage = StorageContext("", "exp1", "trial_0", storage_filesystem=custom)
    mgr = CheckpointManager(
        str(tmp_path / "staging"),
        CheckpointConfig(num_to_keep=2),
        storage=storage,
    )
    local = tmp_path / "reported"
    local.mkdir()
    for i in range(4):
        (local / "data.txt").write_text(f"v{i}")
        mgr.commit(Checkpoint(str(local)), {"loss": 10.0 - i, "i": i})
    # keep-N pruned on the remote filesystem: only the 2 newest survive
    names = sorted(os.listdir(os.path.join(root, "exp1", "trial_0")))
    assert names == ["checkpoint_000002", "checkpoint_000003"]
    latest = mgr.latest()
    with latest.as_directory() as d:
        assert (
            open(os.path.join(d, "data.txt")).read() == "v3"
        )
    assert latest.get_metadata()["metrics"]["i"] == 3


def test_manager_best_by_score_on_storage(tmp_path):
    custom, root = _subtree_fs(tmp_path)
    storage = StorageContext("", "exp2", "trial_0", storage_filesystem=custom)
    mgr = CheckpointManager(
        str(tmp_path / "staging2"),
        CheckpointConfig(
            num_to_keep=2, checkpoint_score_attribute="acc", checkpoint_score_order="max"
        ),
        storage=storage,
    )
    local = tmp_path / "rep2"
    local.mkdir()
    for i, acc in enumerate([0.1, 0.9, 0.5, 0.2]):
        (local / "acc.txt").write_text(str(acc))
        mgr.commit(Checkpoint(str(local)), {"acc": acc})
    # best-by-score kept: 0.9 and 0.5
    assert mgr.best().get_metadata()["metrics"]["acc"] == 0.9
    names = sorted(os.listdir(os.path.join(root, "exp2", "trial_0")))
    assert names == ["checkpoint_000001", "checkpoint_000002"]


def test_restore_after_local_loss(tmp_path):
    """Simulated head death: every local byte vanishes; the URI alone must
    restore the pytree (reference: restoring a Tune run from s3://)."""
    import shutil

    work = tmp_path / "work"
    work.mkdir()
    tree = {"step": np.int64(42), "w": np.ones((4, 4), np.float32) * 3}
    uri = f"file://{tmp_path}/durable/ckpt"
    save_pytree(tree, str(work / "ckpt"), step=42)
    Checkpoint.from_directory(str(work / "ckpt")).to_uri(uri)
    shutil.rmtree(work)  # the "head" and all its local state die

    restored = load_pytree(Checkpoint.from_uri(uri))
    assert int(restored["step"]) == 42
    np.testing.assert_array_equal(restored["w"], np.ones((4, 4), np.float32) * 3)


def test_storage_context_uri_naming(tmp_path):
    ctx = StorageContext(f"file://{tmp_path}/base", "expA", "trial_1")
    assert ctx.uri_for("checkpoint_000000") == (
        f"file://{tmp_path}/base/expA/trial_1/checkpoint_000000"
    )
    fs, p = get_fs_and_path(ctx.uri_for("x"))
    assert p == f"{tmp_path}/base/expA/trial_1/x"
    # experiment-level context (no trial)
    exp_ctx = StorageContext(f"file://{tmp_path}/base", "expA")
    assert exp_ctx.uri_for("state.json").endswith("expA/state.json")
    t = exp_ctx.for_trial("trial_9")
    assert t.uri_for("").endswith("expA/trial_9")


@pytest.fixture
def ray_started():
    import ray_tpu

    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield
    ray_tpu.shutdown()


def test_trainer_fit_with_uri_storage(tmp_path, ray_started):
    """End-to-end: JaxTrainer persists checkpoints to a file:// URI; the
    result checkpoint restores from the URI after the staging dir is gone."""
    import shutil

    from ray_tpu import train
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def loop(config):
        from ray_tpu.train import _session

        ckpt_dir = os.path.join(config["tmp"], "rep")
        for step in range(2):
            tree = {"step": np.int64(step)}
            save_pytree(tree, ckpt_dir, step=step)
            train.report(
                {"loss": 1.0 - step}, checkpoint=Checkpoint.from_directory(ckpt_dir)
            )

    uri = f"file://{tmp_path}/results"
    trainer = JaxTrainer(
        loop,
        train_loop_config={"tmp": str(tmp_path)},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="uri_run", storage_path=uri),
    )
    result = trainer.fit()
    assert result.metrics["loss"] == 0.0
    assert result.checkpoint is not None and result.checkpoint.path.startswith("file://")
    # the checkpoint lives in storage, not in any staging dir
    staging = os.path.expanduser("~/ray_tpu_results/_staging/uri_run")
    shutil.rmtree(staging, ignore_errors=True)
    restored = load_pytree(result.checkpoint)
    assert int(restored["step"]) == 1


def test_tune_run_with_storage_filesystem(tmp_path, ray_started):
    """Tune experiment on an injected pyarrow filesystem: per-trial
    checkpoints + experiment_state.json land on the custom fs."""
    from ray_tpu import train, tune
    from ray_tpu.train import RunConfig

    custom, root = _subtree_fs(tmp_path)

    def trainable(config):
        d = str(tmp_path / f"t{config['x']}")
        save_pytree({"x": np.int64(config["x"])}, d)
        train.report({"score": config["x"]}, checkpoint=Checkpoint.from_directory(d))

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="fs_exp", storage_path="", storage_filesystem=custom),
    )
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.metrics["score"] == 2
    exp_root = os.path.join(root, "fs_exp")
    entries = os.listdir(exp_root)
    assert "experiment_state.json" in entries
    assert any(e.startswith("trial_") for e in entries)
    state = json.load(open(os.path.join(exp_root, "experiment_state.json")))
    assert len(state["trials"]) == 2
    restored = load_pytree(best.checkpoint)
    assert int(restored["x"]) == 2
