"""bench.py section isolation (VERDICT r5 robustness satellite).

One flaky compile (e.g. a dropped remote_compile tunnel) must no longer
zero a whole round's recorded numbers: every section runs behind
``bench._section`` — retry once on failure, emit the section's own JSON
line the moment it finishes, and let the final record carry whatever
sections succeeded.
"""

import contextlib
import io
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _run(sections, name, fn):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        result = bench._section(sections, name, fn)
    lines = [ln for ln in buf.getvalue().splitlines() if ln.startswith("{")]
    return result, lines


def test_section_success_first_try():
    sections = {}
    result, lines = _run(sections, "good", lambda: {"value": 7})
    assert result == {"value": 7}
    assert sections["good"] == {"section": "good", "ok": True, "attempts": 1}
    assert json.loads(lines[-1])["ok"] is True


def test_section_retries_transient_failure_once():
    sections = {}
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise OSError("tunnel reset by peer")
        return {"value": 42}

    result, _ = _run(sections, "flaky", flaky)
    assert result == {"value": 42} and len(calls) == 2
    assert sections["flaky"]["ok"] is True and sections["flaky"]["attempts"] == 2
    # attempt 1's transient error must not linger on a successful record
    assert "error" not in sections["flaky"]


def test_section_double_failure_still_emits_json():
    """Both attempts fail: the section records its error, PRINTS its own
    JSON line anyway (a later crash cannot erase it), and returns None so
    the caller's record goes out with the other sections."""
    sections = {}
    calls = []

    def boom():
        calls.append(1)
        raise RuntimeError("remote_compile tunnel down")

    result, lines = _run(sections, "exploding", boom)
    assert result is None and len(calls) == 2
    rec = json.loads(lines[-1])
    assert rec["section"] == "exploding" and rec["ok"] is False
    assert "remote_compile tunnel down" in rec["error"]


def test_section_empty_result_counts_as_failure():
    """Subprocess-wrapped sections signal failure by returning {} — the
    wrapper must retry and record the miss instead of treating empty as
    success."""
    sections = {}
    result, _ = _run(sections, "empty", dict)
    assert not result  # falsy either way; callers use `or {}`
    assert sections["empty"]["ok"] is False
    assert sections["empty"]["error"] == "empty result"


def test_failed_sections_do_not_stop_later_ones():
    sections = {}
    _run(sections, "a", lambda: (_ for _ in ()).throw(ValueError("x")))
    result, _ = _run(sections, "b", lambda: {"value": 1})
    assert result == {"value": 1}
    assert sections["a"]["ok"] is False and sections["b"]["ok"] is True
