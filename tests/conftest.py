"""Shared fixtures.

Mirrors the reference's ``python/ray/tests/conftest.py``: ``ray_start_regular``
(single-node init/shutdown per test), ``ray_start_cluster`` (in-process
multi-node). JAX-touching tests force an 8-device virtual CPU mesh so
multi-chip sharding logic runs in CI with no TPU attached (the reference
equivalently fakes GPUs with logical resources).
"""

import os

# Must be set before jax ever initializes in this process: tests exercise
# multi-"chip" sharding on a virtual 8-device CPU mesh. The env vars alone are
# not enough in environments whose site hooks pre-register a TPU plugin, so
# also force the platform through jax.config (no-op if jax is absent).
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
# CI never touches the TPU: drop the axon plugin bootstrap env so WORKER
# subprocesses skip the relay handshake in their sitecustomize — python
# process startup otherwise BLOCKS whenever the single-tenant TPU tunnel
# is busy (and CPU tests have no business dialing it at all). Invoke
# pytest itself with PALLAS_AXON_POOL_IPS= for the same reason.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_ENABLE_X64", "0")
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass

import pytest  # noqa: E402

import ray_tpu  # noqa: E402


@pytest.fixture
def ray_start_regular():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_2_cpus():
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster_holder = []

    def factory(**head_args):
        cluster = Cluster(initialize_head=True, head_node_args=head_args)
        cluster_holder.append(cluster)
        return cluster

    yield factory
    for c in cluster_holder:
        c.shutdown()


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running learning tests")


def pytest_runtest_logreport(report):
    """Failures land in the flight recorder too, so the flushed ring
    interleaves 'which test failed' with the runtime events around it."""
    if report.failed and report.when == "call":
        try:
            from ray_tpu._private import events

            events.record("ci.test_failed", test=report.nodeid)
        except Exception:
            pass


def pytest_sessionfinish(session, exitstatus):
    """On a failing run, flush THIS process's flight-recorder ring to
    ``RAY_TPU_EVENTS_DIR`` so CI can upload it as a postmortem artifact
    next to the worker rings (those crash-flush themselves on the SIGTERM
    that kills them — _private/events.py).  A green run writes nothing."""
    if exitstatus == 0:
        return
    try:
        from ray_tpu._private import events

        events.flush(reason=f"pytest-exit-{exitstatus}")
    except Exception:
        pass  # never let observability turn a test failure into an error


# the BENCH_r06 spin canary, shared by the load-tolerant tests
# (test_worker_forkserver's spawn wave, test_multihost's CLI roundtrip):
# integer adds per second — this box idles at ~24-29 Mops (BENCH_r06-r08),
# a saturated run measures <10
SPIN_CANARY_FLOOR_MOPS = 12.0


def spin_mops(n: int = 2_000_000) -> float:
    import time as _time

    t0 = _time.perf_counter()
    x = 0
    for i in range(n):
        x += i
    return n / (_time.perf_counter() - t0) / 1e6
