"""Cross-request prefix cache (``ray_tpu.llm.prefix_cache``).

The correctness bar is hard: outputs must be TOKEN-IDENTICAL with the
cache on vs off — under greedy AND seeded sampling, composed with
speculative decoding, preemption-recompute, and mid-stream failover
resume — because prefix reuse is exact (causal attention: identical
prefixes ⇒ identical KV), never approximate.  Plus: radix-tree goldens
(insert/match/intra-block CoW split/LRU evict), the pool's refcounted
ledger with copy-on-write sharing, an eviction-under-pressure soak that
must end with clean pool AND tree audits, prefix-aware cross-request
drafting, the weight-swap flush, and the observability surface
(``llm.prefix.*`` events, ``llm_prefix_cache_*`` metrics, grafana row).
"""

import queue
import time

import numpy as np
import pytest

import jax

from ray_tpu._private import events as _events
from ray_tpu.llm import (
    CacheConfig,
    EngineConfig,
    EngineWatchdog,
    KVBlockPool,
    LLMEngine,
    NGramDrafter,
    PrefixCache,
    SamplingParams,
)
from ray_tpu.llm.prefix_cache import METRIC_NAMES
from ray_tpu.models.gptj import GPTJConfig, gptj_init

TINY = GPTJConfig(
    vocab_size=128, seq_len=64, d_model=32, n_layers=2, n_heads=2,
    rotary_dim=8, dtype="float32", remat=False, attn_impl="xla",
    fused_loss=False,
)

GREEDY = SamplingParams(max_tokens=12)
SAMPLED = SamplingParams(max_tokens=12, temperature=0.8, top_k=5, top_p=0.9,
                         seed=77)

# prompts engineered around block_size=4: a 8-token shared head, then
# per-request divergence either ON a block boundary or INSIDE a block
SHARED = [5, 6, 7, 5, 9, 2, 4, 8]
PROMPTS = [
    SHARED + [1, 3],               # boundary divergence
    SHARED + [1, 9],               # diverges INSIDE the third block (CoW)
    SHARED + [2, 2, 6, 6, 3],      # longer tail
    SHARED[:4] + [9, 9, 1, 1, 7],  # only one block shared
    [3, 1, 4, 1, 5, 9, 2, 6],      # no shared prefix at all
]


@pytest.fixture(scope="module")
def tiny_params():
    return gptj_init(jax.random.PRNGKey(0), TINY)


def _engine(params, cached, **kw):
    defaults = dict(
        max_slots=3, num_blocks=32, block_size=4, max_blocks_per_seq=12,
        prefill_chunk=8, prefix_cache=cached,
    )
    defaults.update(kw)
    return LLMEngine(TINY, params, EngineConfig(**defaults))


@pytest.fixture(scope="module")
def pair(tiny_params):
    """(cache-on, cache-off) engines — the identity-matrix workhorses."""
    return _engine(tiny_params, True), _engine(tiny_params, False)


@pytest.fixture(scope="module")
def spec_pair(tiny_params):
    return (
        _engine(tiny_params, True, spec_k=2),
        _engine(tiny_params, False, spec_k=2),
    )


def _drain(eng, req):
    deadline = time.time() + 60
    while not req.finished:
        eng.step()
        assert time.time() < deadline, "engine made no progress"
    got = []
    while True:
        try:
            kind, val = req.stream.get_nowait()
        except queue.Empty:
            break
        if kind == "token":
            got.append(val)
        else:
            break
    return got


# ---------------------------------------------------------------------------
# pool refcounts + copy-on-write ledger


class TestPoolRefcounts:
    def _pool(self, num_blocks=9, block_size=4, bps=6):
        return KVBlockPool(
            CacheConfig(num_blocks=num_blocks, block_size=block_size,
                        max_blocks_per_seq=bps),
            n_layers=1, n_heads=1, head_dim=4,
        )

    def test_shared_allocate_refcounts_and_free_order(self):
        pool = self._pool()
        a = pool.allocate("a", 12)                     # 3 exclusive blocks
        for b in a[:2]:
            assert pool.cache_retain(b)                # tree retains 2
        assert pool.ref(a[0]) == 2
        shared = a[:2]
        b = pool.allocate("b", 12, shared=shared)      # 2 shared + 1 fresh
        assert b[:2] == shared and b[2] != a[2]
        assert pool.ref(shared[0]) == 3
        assert pool.num_used_blocks == 4               # distinct, not 6
        # free the ORIGINAL owner: shared blocks survive on b + cache refs
        freed = pool.free("a")
        assert freed == 1                              # only a's tail block
        assert pool.ref(shared[0]) == 2
        assert pool.free("b") == 1
        # now cache-only: evictable, not free
        assert pool.is_evictable(shared[0]) and pool.is_evictable(shared[1])
        assert pool.num_free_blocks == 6
        assert pool.cache_release(shared[0])           # back to the free list
        assert pool.num_free_blocks == 7
        assert pool.audit()["ok"]

    def test_allocate_validates_shared(self):
        pool = self._pool()
        a = pool.allocate("a", 8)
        with pytest.raises(ValueError, match="not cache-resident"):
            pool.allocate("b", 8, shared=[a[0]])       # owned but NOT cached
        pool.cache_retain(a[0])
        with pytest.raises(ValueError, match="exclusive"):
            pool.allocate("c", 4, shared=[a[0]])       # shared >= need
        with pytest.raises(ValueError, match="not cache-resident"):
            pool.allocate("d", 8, shared=[99])

    def test_cache_retain_rejects_free_and_double(self):
        pool = self._pool()
        assert not pool.cache_retain(3)                # free block: no resurrect
        a = pool.allocate("a", 4)
        assert pool.cache_retain(a[0])
        assert not pool.cache_retain(a[0])             # one node per block
        assert not pool.cache_release(a[0] + 1)        # not held

    def test_audit_partitions_shared_and_cached(self):
        pool = self._pool()
        a = pool.allocate("a", 8)
        pool.cache_retain(a[0])
        pool.allocate("b", 8, shared=[a[0]])
        audit = pool.audit()
        assert audit["ok"]
        assert audit["shared"] == 1 and audit["cached"] == 1
        assert audit["cached_only"] == 0 and audit["ref_errors"] == 0
        pool.free("a"), pool.free("b")
        audit = pool.audit()
        assert audit["ok"] and audit["cached_only"] == 1
        # corrupt a refcount: the audit must name it
        pool._ref[a[0]] = 5
        bad = pool.audit()
        assert not bad["ok"] and bad["ref_errors"] == 1

    def test_shrink_to_derefs_tail(self):
        pool = self._pool()
        pool.allocate("a", 20)                         # 5 blocks
        free0 = pool.num_free_blocks
        assert pool.shrink_to("a", 8) == 3
        assert pool.num_free_blocks == free0 + 3
        assert pool.audit()["ok"]


# ---------------------------------------------------------------------------
# radix tree goldens (host-only: match / insert / split / evict)


class TestRadixTree:
    def _setup(self, num_blocks=20, bs=4):
        pool = KVBlockPool(
            CacheConfig(num_blocks=num_blocks, block_size=bs,
                        max_blocks_per_seq=10),
            n_layers=1, n_heads=1, head_dim=4,
        )
        return pool, PrefixCache(pool)

    def test_empty_tree_no_match(self):
        _, cache = self._setup()
        m = cache.match([1, 2, 3, 4, 5, 6, 7, 8, 9])
        assert m.blocks == () and m.matched == 0 and m.cow_src is None

    def test_insert_then_match_full_blocks(self):
        pool, cache = self._setup()
        toks = [1, 2, 3, 4, 5, 6, 7, 8, 9, 9]
        blocks = pool.allocate("a", len(toks))
        assert cache.insert(toks, blocks, limit=len(toks)) == 2  # 2 full blocks
        m = cache.match(toks)
        assert list(m.blocks) == blocks[:2] and m.matched == 8
        assert pool.ref(blocks[0]) == 2                # seq + tree

    def test_match_caps_at_len_minus_one(self):
        pool, cache = self._setup()
        toks = [1, 2, 3, 4, 5, 6, 7, 8]
        blocks = pool.allocate("a", len(toks))
        cache.insert(toks, blocks, limit=8)
        m = cache.match(toks)                          # identical prompt
        # 8 tokens cached but one must remain to prefill: 1 full block +
        # a 3-token CoW split of the second
        assert len(m.blocks) == 1 and m.matched == 7
        assert m.cow_src == blocks[1] and m.cow_tokens == 3

    def test_intra_block_split_cow(self):
        pool, cache = self._setup()
        toks = [1, 2, 3, 4, 5, 6, 7, 8]
        blocks = pool.allocate("a", len(toks))
        cache.insert(toks, blocks, limit=8)
        m = cache.match([1, 2, 3, 4, 5, 6, 9, 9, 9, 9])
        assert list(m.blocks) == [blocks[0]]
        assert m.cow_src == blocks[1] and m.cow_tokens == 2 and m.matched == 6

    def test_cow_min_tokens_gate(self):
        pool, _ = self._setup()
        cache = PrefixCache(pool, cow_min_tokens=3)
        toks = [1, 2, 3, 4, 5, 6, 7, 8]
        blocks = pool.allocate("a", len(toks))
        cache.insert(toks, blocks, limit=8)
        m = cache.match([1, 2, 3, 4, 5, 6, 9, 9, 9])
        assert m.cow_src is None and m.matched == 4    # 2 < min 3: no fork

    def test_insert_dedupes_existing_nodes(self):
        pool, cache = self._setup()
        toks = [1, 2, 3, 4, 5, 6, 7, 8, 1]
        a = pool.allocate("a", len(toks))
        cache.insert(toks, a, limit=8)
        b = pool.allocate("b", len(toks))              # same content, own blocks
        assert cache.insert(toks, b, limit=8) == 0     # nothing new
        assert cache.stats()["nodes"] == 2
        m = cache.match(toks)
        assert list(m.blocks) == a[:2]                 # the ORIGINAL copies

    def test_lru_eviction_leaf_first(self):
        pool, cache = self._setup()
        t1 = [1, 2, 3, 4, 5, 6, 7, 8, 0]
        t2 = [1, 2, 3, 4, 9, 9, 9, 9, 0]
        a = pool.allocate("a", len(t1))
        cache.insert(t1, a, limit=8)                   # chain: A0 -> A1
        b = pool.allocate("b", len(t2))
        cache.insert(t2, b, limit=8)                   # A0 -> B1 (shared head)
        pool.free("a"), pool.free("b")
        # everything cache-only now; t2's leaf was used more recently
        cache.match(t2)
        assert cache.evict(1) == 1                     # evicts t1's leaf (LRU)
        assert cache.match(t1).matched == 4            # head survives
        assert cache.match(t2).matched == 8
        # the shared head is NOT a leaf: unevictable until children go
        assert cache.evict(10) == 2                    # B1 leaf, then the head
        assert cache.stats()["nodes"] == 0
        assert pool.num_free_blocks == pool.cfg.num_blocks - 1
        assert pool.audit()["ok"] and cache.audit()["ok"]

    def test_evict_skips_protected_and_pinned(self):
        pool, cache = self._setup()
        toks = [1, 2, 3, 4, 5, 6, 7, 8, 0]
        a = pool.allocate("a", len(toks))
        cache.insert(toks, a, limit=8)
        # pinned: "a" still owns the blocks -> nothing evictable
        assert cache.evict(5) == 0
        pool.free("a")
        # protected: an in-flight admission is about to share the leaf
        assert cache.evict(5, protect=frozenset(a[:2])) == 0
        assert cache.evict(5) == 2

    def test_flush_releases_everything(self):
        pool, cache = self._setup()
        toks = [1, 2, 3, 4, 5, 6, 7, 8, 0]
        a = pool.allocate("a", len(toks))
        cache.insert(toks, a, limit=8)
        pool.free("a")
        assert cache.flush(reason="test") == 2
        assert cache.stats()["nodes"] == 0
        assert pool.num_free_blocks == pool.cfg.num_blocks - 1
        assert pool.audit()["ok"]

    def test_audit_catches_dangling(self):
        pool, cache = self._setup()
        toks = [1, 2, 3, 4, 5]
        a = pool.allocate("a", len(toks))
        cache.insert(toks, a, limit=4)
        assert cache.audit()["ok"]
        # simulate a dangling tree reference (release behind its back)
        pool.cache_release(a[0])
        bad = cache.audit()
        assert not bad["ok"] and bad["dangling"] == [a[0]]

    def test_paths_recency_order(self):
        pool, cache = self._setup()
        t1 = [1, 2, 3, 4, 0]
        t2 = [9, 8, 7, 6, 0]
        a = pool.allocate("a", len(t1))
        cache.insert(t1, a, limit=4)
        b = pool.allocate("b", len(t2))
        cache.insert(t2, b, limit=4)
        cache.match(t1)                                 # t1 most recent
        p = cache.paths()
        assert p[0] == [1, 2, 3, 4] and p[1] == [9, 8, 7, 6]


# ---------------------------------------------------------------------------
# the identity matrix: cache on/off × greedy/seeded × spec × preempt × resume


class TestIdentityMatrix:
    @pytest.mark.parametrize("params", [GREEDY, SAMPLED],
                             ids=["greedy", "sampled"])
    def test_plain_engine_identity(self, pair, params):
        on, off = pair
        ref = [off.generate(p, params) for p in PROMPTS]
        cold = [on.generate(p, params) for p in PROMPTS]
        warm = [on.generate(p, params) for p in PROMPTS]  # now fully cached
        assert cold == ref and warm == ref
        assert on.stats()["prefix_cache"]["hit_tokens"] > 0
        assert on.pool.audit()["ok"] and on.prefix_cache.audit()["ok"]

    @pytest.mark.parametrize("params", [GREEDY, SAMPLED],
                             ids=["greedy", "sampled"])
    def test_spec_decode_identity(self, spec_pair, params):
        on, off = spec_pair
        ref = [off.generate(p, params) for p in PROMPTS]
        assert [on.generate(p, params) for p in PROMPTS] == ref  # cold
        assert [on.generate(p, params) for p in PROMPTS] == ref  # warm
        assert on.pool.audit()["ok"] and on.prefix_cache.audit()["ok"]

    def test_spec_and_plain_agree_with_cache(self, pair, spec_pair):
        """Transitively: spec+cache == plain no-cache (greedy)."""
        assert [spec_pair[0].generate(p, GREEDY) for p in PROMPTS] == [
            pair[1].generate(p, GREEDY) for p in PROMPTS
        ]

    @pytest.mark.parametrize("params", [GREEDY, SAMPLED],
                             ids=["greedy", "sampled"])
    def test_preemption_identity(self, tiny_params, params):
        """A pool too small for the whole batch: preemption-recompute and
        cache admission compose, outputs stay identical to cache-off."""
        def run(cached):
            eng = _engine(tiny_params, cached, max_slots=3, num_blocks=14,
                          max_blocks_per_seq=10, prefill_chunk=4)
            p = SamplingParams(
                max_tokens=18, temperature=params.temperature,
                top_k=params.top_k, top_p=params.top_p, seed=params.seed,
            )
            reqs = [eng.submit(pr[:8], p) for pr in PROMPTS[:3]]
            outs = [_drain(eng, r) for r in reqs]
            return eng, outs

        on, got = run(True)
        off, ref = run(False)
        assert got == ref
        assert on.pool.audit()["ok"] and on.prefix_cache.audit()["ok"]

    @pytest.mark.parametrize("params", [GREEDY, SAMPLED],
                             ids=["greedy", "sampled"])
    def test_failover_resume_identity(self, pair, params):
        """Mid-stream failover onto a WARM replica: resume_tokens + a
        cached prefix of the replayed prompt+out sequence still continue
        token-identically at every cut."""
        on, off = pair
        full = off.generate(PROMPTS[0], params)
        on.generate(PROMPTS[0], params)                # warm the tree
        for cut in (0, 1, 5, len(full) - 1, len(full)):
            req = on.submit(PROMPTS[0], params, resume_tokens=full[:cut])
            got = _drain(on, req)
            assert full[:cut] + got == full, f"cut={cut}"
        assert on.pool.audit()["ok"] and on.prefix_cache.audit()["ok"]


# ---------------------------------------------------------------------------
# CoW fork correctness at the device level


class TestCopyOnWrite:
    def test_fork_blocks_copies_content(self, tiny_params):
        eng = _engine(tiny_params, True)
        eng.generate([1, 2, 3, 4, 5, 6, 7, 8, 9], GREEDY)
        pool = eng.pool
        src = 1
        dst = pool.cfg.num_blocks - 1
        src_arr = np.zeros(eng.cfg.max_slots, np.int32)
        dst_arr = np.zeros(eng.cfg.max_slots, np.int32)
        src_arr[0], dst_arr[0] = src, dst
        pool.k, pool.v = eng.runner.fork_blocks(pool.k, pool.v, src_arr, dst_arr)
        np.testing.assert_array_equal(
            np.asarray(pool.k[:, src]), np.asarray(pool.k[:, dst])
        )
        np.testing.assert_array_equal(
            np.asarray(pool.v[:, src]), np.asarray(pool.v[:, dst])
        )

    def test_cow_admission_forks_and_matches(self, tiny_params):
        """A prompt diverging INSIDE a cached block must CoW-fork (event
        + counter) and produce the same output as a cold engine."""
        eng = _engine(tiny_params, True)
        off = _engine(tiny_params, False)
        base = [1, 2, 3, 4, 5, 6, 7, 8, 9]
        div = [1, 2, 3, 4, 5, 9, 9, 9, 9]     # diverges at block-1 offset 1
        eng.generate(base, GREEDY)
        forks0 = eng.prefix_cache.stats()["cow_forks"]
        assert eng.generate(div, GREEDY) == off.generate(div, GREEDY)
        assert eng.prefix_cache.stats()["cow_forks"] == forks0 + 1
        assert eng.pool.audit()["ok"]


# ---------------------------------------------------------------------------
# eviction soak + watchdog composition


class TestEvictionSoak:
    def test_soak_under_kv_pressure_ends_clean(self, tiny_params):
        """Many distinct prompts from a few shared families through a pool
        far too small to retain them all: admission evicts LRU cached
        blocks, preemption still works, and the final pool AND tree
        audits are clean (the watchdog's composed view included)."""
        eng = _engine(tiny_params, True, max_slots=2, num_blocks=16,
                      max_blocks_per_seq=10, prefill_chunk=4)
        rng = np.random.RandomState(0)
        fams = [list(rng.randint(0, TINY.vocab_size, 8)) for _ in range(3)]
        reqs = []
        for i in range(24):
            fam = fams[i % len(fams)]
            prompt = fam + list(rng.randint(0, TINY.vocab_size, 4))
            reqs.append(eng.submit(prompt, SamplingParams(max_tokens=6)))
            eng.step()
        for r in reqs:
            _drain(eng, r)
        s = eng.prefix_cache.stats()
        assert s["hit_tokens"] > 0, "families never hit the cache"
        assert s["evicted_blocks"] > 0, "the pool never saw pressure"
        assert eng.pool.audit()["ok"], eng.pool.audit()
        assert eng.prefix_cache.audit()["ok"], eng.prefix_cache.audit()
        wd = EngineWatchdog(eng)
        info = wd.check_once()
        assert info["audit"]["ok"]
        assert info["audit"]["prefix_cache"]["ok"]

    def test_watchdog_flags_dangling_tree_reference(self, tiny_params):
        eng = _engine(tiny_params, True)
        eng.generate([1, 2, 3, 4, 5, 6, 7, 8, 9], GREEDY)
        wd = EngineWatchdog(eng)
        assert wd.check_once()["audit"]["ok"]
        blk = next(iter(eng.prefix_cache._by_block))
        eng.pool.cache_release(blk)                    # corrupt: node remains
        info = wd.check_once()
        assert not info["audit"]["ok"]
        assert info["audit"]["prefix_cache"]["dangling"] == [blk]
        assert wd.leak_count == 1


# ---------------------------------------------------------------------------
# prefix-aware drafting, weight-swap flush, observability surface


class TestPrefixAwareDrafting:
    def test_corpus_match_drafts_from_shared_paths(self):
        d = NGramDrafter(k=3, max_ngram=3)
        # the continuation of (7, 8) lives ONLY in the shared corpus
        d.corpus = lambda: [[1, 2, 7, 8, 40, 41, 42, 43]]
        out = d.propose([[9, 9, 9, 7, 8]])
        assert out.tolist() == [[40, 41, 42]]
        assert d.last_matched.tolist() == [True]

    def test_local_match_still_wins(self):
        d = NGramDrafter(k=2, max_ngram=3)
        d.corpus = lambda: [[5, 6, 99, 99]]
        out = d.propose([[5, 6, 1, 2, 5, 6]])          # local bigram match
        assert out.tolist() == [[1, 2]]

    def test_no_corpus_single_token_is_noise(self):
        d = NGramDrafter(k=2, max_ngram=3)
        d.corpus = lambda: [[7, 40, 41]]               # only n=1 would match
        out = d.propose([[1, 2, 3, 7]])
        assert d.last_matched.tolist() == [False]
        assert out.tolist() == [[7, 7]]                # repeat-last fallback

    def test_engine_wires_corpus(self, tiny_params):
        eng = _engine(tiny_params, True, spec_k=2)
        assert eng._drafter.corpus is not None
        eng2 = _engine(tiny_params, False, spec_k=2)
        assert eng2._drafter.corpus is None


class TestWeightSwapFlush:
    def test_update_weights_flushes_tree(self, tiny_params):
        eng = _engine(tiny_params, True)
        off = _engine(tiny_params, False)
        prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]
        ref = off.generate(prompt, GREEDY)
        eng.generate(prompt, GREEDY)
        assert eng.prefix_cache.stats()["nodes"] > 0
        eng.update_weights(eng.runner.params)          # same params, new version
        assert eng.prefix_cache.stats()["nodes"] == 0  # stale KV dropped
        assert eng.pool.num_free_blocks == eng.pool.cfg.num_blocks - 1
        assert eng.generate(prompt, GREEDY) == ref     # recomputed, identical
        assert eng.pool.audit()["ok"]


    def test_mid_prefill_weight_swap_never_reinserts_stale_kv(self, tiny_params):
        """The epoch guard: a request whose chunked prefill STRADDLES an
        update_weights flush computed (some of) its KV under the old
        parameters — its later prefill chunks must not re-register blocks
        into the flushed tree, or a follow-up request would seed stale
        KV and diverge from the cache-off engine."""
        v2 = gptj_init(jax.random.PRNGKey(9), TINY)
        eng = _engine(tiny_params, True, prefill_chunk=4)
        prompt = list(np.random.RandomState(5).randint(0, TINY.vocab_size, 12))
        req = eng.submit(prompt, GREEDY)
        eng.step()                                     # admit + first chunk only
        assert req.prefill_pos < len(prompt)
        eng.update_weights(v2)                         # flush mid-prefill
        _drain(eng, req)                               # finishes under v2
        # the straddling request's blocks never re-entered the tree
        assert eng.prefix_cache.stats()["nodes"] == 0
        # a fresh request prefills under v2 throughout and must match a
        # pure-v2 engine exactly (and MAY now populate the tree)
        ref = _engine(v2, False).generate(prompt, GREEDY)
        assert eng.generate(prompt, GREEDY) == ref
        assert eng.prefix_cache.stats()["nodes"] > 0
        assert eng.generate(prompt, GREEDY) == ref     # warm, still v2-exact
        assert eng.pool.audit()["ok"] and eng.prefix_cache.audit()["ok"]


class TestObservability:
    def test_prefix_events_and_stats(self, tiny_params):
        _events.clear()
        eng = _engine(tiny_params, True)
        prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]
        eng.generate(prompt, GREEDY)
        eng.generate(prompt, GREEDY)
        types = [e["type"] for e in _events.snapshot()]
        assert "llm.prefix.insert" in types
        assert "llm.prefix.hit" in types
        hit = next(
            e for e in _events.snapshot() if e["type"] == "llm.prefix.hit"
        )
        assert hit["matched_tokens"] > 0 and hit["engine_req"]
        admit = [e for e in _events.snapshot() if e["type"] == "llm.admit"]
        assert admit[-1]["cached_tokens"] == hit["matched_tokens"]
        s = eng.stats()
        assert s["prefix_cache"]["hit_rate"] > 0
        assert s["prefill_tokens_computed"] > 0

    def test_grafana_row_matches_metric_names(self):
        """The dashboard's prefix row must not drift from the metric
        family the cache actually exports (prefix_cache.METRIC_NAMES)."""
        from ray_tpu.util.grafana import dashboard_json

        doc = str(dashboard_json())
        for name in METRIC_NAMES:
            assert name in doc, f"grafana row missing {name}"

    def test_observability_doc_names_the_family(self):
        import pathlib

        doc = pathlib.Path(__file__).parent.parent / "OBSERVABILITY.md"
        text = doc.read_text()
        assert "llm.prefix.*" in text
        for name in METRIC_NAMES:
            assert name in text, f"OBSERVABILITY.md missing {name}"

    def test_serve_autoscaling_metrics_include_hit_rate(self, tiny_params):
        from ray_tpu.serve.llm import LLMDeployment

        dep = LLMDeployment.__new__(LLMDeployment)
        dep._engine = _engine(tiny_params, True)
        m = dep.autoscaling_metrics()
        assert "prefix_hit_rate" in m
        dep._engine = _engine(tiny_params, False)
        assert "prefix_hit_rate" not in dep.autoscaling_metrics()
