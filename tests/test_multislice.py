"""Multi-slice hybrid meshes (parallel/multislice.py): dp crosses slice
(DCN) boundaries slice-major, every other axis stays within a slice (ICI).
Reference mental model: the multi-slice scaling recipe (SURVEY §7) /
jax mesh_utils.create_hybrid_device_mesh."""

import numpy as np
import pytest

from ray_tpu.parallel.mesh import MeshConfig
from ray_tpu.parallel.multislice import make_multislice_mesh, slice_groups


def _devices(n=8):
    import jax

    return jax.devices("cpu")[:n]


def test_slice_groups_contiguous():
    devs = _devices(8)
    groups = slice_groups(devs, 2)
    assert [len(g) for g in groups] == [4, 4]
    assert groups[0] == devs[:4] and groups[1] == devs[4:]
    with pytest.raises(ValueError, match="divisible"):
        slice_groups(devs[:6], 4)


def test_dp_axis_is_slice_major():
    devs = _devices(8)
    mesh = make_multislice_mesh(
        MeshConfig(dp=4, fsdp=1, tp=2, sp=1), num_slices=2, devices=devs
    )
    arr = np.asarray(mesh.devices)  # axes (dp, fsdp, ep, sp, tp)
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    flat_dp = arr.reshape(4, 2)  # (dp, tp)
    # dp-major halves = slices: first two dp rows from slice 0, last two
    # from slice 1 — cross-slice traffic is dp-only
    slice_of = {d: 0 for d in devs[:4]} | {d: 1 for d in devs[4:]}
    dp_slices = [{slice_of[d] for d in row} for row in flat_dp]
    assert dp_slices == [{0}, {0}, {1}, {1}]
    # tp groups never cross a slice
    for row in flat_dp:
        assert len({slice_of[d] for d in row}) == 1


def test_dp_must_cover_slices():
    devs = _devices(8)
    with pytest.raises(ValueError, match="multiple of the slice count"):
        make_multislice_mesh(
            MeshConfig(dp=1, fsdp=1, tp=8, sp=1), num_slices=2, devices=devs
        )


def test_single_slice_degenerates_to_plain_mesh():
    devs = _devices(4)
    mesh = make_multislice_mesh(
        MeshConfig(dp=2, fsdp=1, tp=2, sp=1), num_slices=1, devices=devs
    )
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2


def _worker_can_size_cpu_devices() -> bool:
    """Capability probe for the two-process DCN dryrun: each worker
    subprocess sizes its local device count via
    ``jax.config.update("jax_num_cpu_devices", n)``
    (parallel/_multislice_worker.py). jax builds without that config
    option (observed on 0.4.37 here — a documented pre-existing
    environmental failure since PR 9) kill every worker at startup with
    ``AttributeError: Unrecognized config option``, so the test cannot
    exercise what it is about. The probe checks the option exists
    without mutating anything."""
    import jax

    return hasattr(jax.config, "jax_num_cpu_devices")


@pytest.mark.skipif(
    not _worker_can_size_cpu_devices(),
    reason="jax build lacks the jax_num_cpu_devices config option the "
    "multislice worker needs (pre-existing environmental failure, "
    "documented since PR 9)",
)
def test_two_process_dcn_dp():
    """REAL multi-process multislice: 2 subprocesses jax.distributed-join
    one 8-device mesh; dp gradient reduction crosses the process boundary
    (gloo = the DCN stand-in); all ranks must agree bit-for-bit and the
    loss must decrease. Reference counterpart: the cross-host process group
    built by python/ray/train/torch/config.py:47-91."""
    from ray_tpu.parallel.multislice import launch_multislice_procs

    losses = launch_multislice_procs(num_procs=2, local_devices=4, steps=2)
    assert losses[0] == losses[1]
    assert losses[0][1] < losses[0][0]
