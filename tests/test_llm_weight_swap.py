"""RL009's runtime twin: a params hot-swap must reach EVERY jitted
model-runner entry point.

The PR 7 latent bug was exactly this failing silently: ``_embed``/
``_lm_head`` read ``self.params`` at trace time, so the embed/pos/ln_f/
lm_head weights were baked into the compiled executables and
``LLMEngine.update_weights`` swapped only the layer stack. raylint RL009
now catches that shape statically; this suite is the dynamic guard — it
swaps ``runner.params`` (the same untraced attribute assignment
``update_weights`` performs) and asserts each jitted path
(``prefill_chunk``, ``decode_step``, ``verify_step``) produces outputs
identical to a FRESH runner built from the swapped params, and different
from the pre-swap outputs. ``fork_blocks`` is asserted params-independent
(a pure device block copy) so all four entry points are pinned.
"""

import numpy as np
import pytest

import jax

from ray_tpu.llm.cache import CacheConfig, KVBlockPool
from ray_tpu.llm.model_runner import PagedModelRunner
from ray_tpu.models.gptj import GPTJConfig, gptj_init

CFG = GPTJConfig(
    vocab_size=64, seq_len=64, d_model=32, n_layers=2, n_heads=2,
    rotary_dim=8, dtype="float32", remat=False, attn_impl="xla",
    fused_loss=False,
)
BLOCK_SIZE = 4
SLOTS = 2
SPEC_W = 3  # verify window: 1 emitted + 2 drafted


@pytest.fixture(scope="module")
def params_pair():
    a = gptj_init(jax.random.PRNGKey(0), CFG)
    b = gptj_init(jax.random.PRNGKey(1), CFG)
    return a, b


def _pool():
    return KVBlockPool(
        CacheConfig(num_blocks=16, block_size=BLOCK_SIZE, max_blocks_per_seq=8),
        n_layers=CFG.n_layers, n_heads=CFG.n_heads, head_dim=CFG.head_dim,
        dtype=CFG.dtype,
    )


def _drive(runner):
    """One prefill chunk + one batched decode + one verify window against a
    fresh pool; returns every jitted entry point's observable output."""
    pool = _pool()
    rng = np.random.RandomState(7)
    prompt = rng.randint(1, CFG.vocab_size, 8).astype(np.int32)
    pool.allocate("s0", 12)
    pool.allocate("s1", 12)
    table0 = pool.table_row("s0")

    k, v, last_logits = runner.prefill_chunk(
        pool.k, pool.v, prompt, 0, len(prompt), table0
    )
    pool.k, pool.v = k, v

    tables = np.stack([pool.table_row("s0"), pool.table_row("s1")])
    tokens = np.array([prompt[-1], prompt[0]], np.int32)
    positions = np.array([len(prompt), 0], np.int32)
    greedy = np.zeros(SLOTS, np.float32)
    top_k = np.zeros(SLOTS, np.int32)
    top_p = np.ones(SLOTS, np.float32)
    seeds = np.zeros(SLOTS, np.uint32)
    counters = np.zeros(SLOTS, np.int32)
    k, v, nxt, logp = runner.decode_step(
        pool.k, pool.v, tokens, positions, tables,
        greedy, top_k, top_p, seeds, counters,
    )
    pool.k, pool.v = k, v

    win = np.tile(prompt[:SPEC_W], (SLOTS, 1)).astype(np.int32)
    base_pos = np.array([len(prompt) + 1, 1], np.int32)
    k, v, n_acc, out, out_lp = runner.verify_step(
        pool.k, pool.v, win, base_pos, tables,
        greedy, top_k, top_p, seeds, counters,
    )
    pool.k, pool.v = k, v
    return {
        "prefill_logits": np.asarray(last_logits),
        "decode_tokens": np.asarray(nxt),
        "decode_logprobs": np.asarray(logp),
        "verify_accepted": np.asarray(n_acc),
        "verify_tokens": np.asarray(out),
        "verify_logprobs": np.asarray(out_lp),
        "pool": pool,
        "runner": runner,
    }


def test_every_jitted_entry_point_reflects_param_swap(params_pair):
    params_a, params_b = params_pair
    runner = PagedModelRunner(CFG, params_a, BLOCK_SIZE, attn_impl="xla")
    before = _drive(runner)

    # the exact swap update_weights performs: reassign the attribute, no
    # re-jit — the executables must pick up the new params via the traced
    # argument, or this whole test is comparing stale constants
    runner.params = params_b
    after = _drive(runner)
    fresh = _drive(PagedModelRunner(CFG, params_b, BLOCK_SIZE, attn_impl="xla"))

    for key in (
        "prefill_logits", "decode_tokens", "decode_logprobs",
        "verify_accepted", "verify_tokens", "verify_logprobs",
    ):
        np.testing.assert_allclose(
            after[key], fresh[key], rtol=1e-5, atol=1e-5,
            err_msg=f"{key}: swapped runner diverges from fresh runner — "
            "some weights are baked into the jitted executable",
        )
    # and the swap must actually CHANGE the outputs, or the assertions
    # above would pass vacuously on params-independent garbage
    assert not np.allclose(before["prefill_logits"], after["prefill_logits"])
    assert not np.allclose(before["decode_logprobs"], after["decode_logprobs"])


def test_fork_blocks_is_params_independent(params_pair):
    params_a, params_b = params_pair
    runner = PagedModelRunner(CFG, params_a, BLOCK_SIZE, attn_impl="xla")
    state = _drive(runner)
    pool = state["pool"]
    src_block = pool.blocks_of("s0")[0]
    dst_block = pool.blocks_of("s1")[-1]
    lanes_src = np.zeros(SLOTS, np.int32)
    lanes_dst = np.zeros(SLOTS, np.int32)
    lanes_src[0], lanes_dst[0] = src_block, dst_block

    runner.params = params_b  # swap BEFORE the fork: the copy must not care
    k, v = runner.fork_blocks(pool.k, pool.v, lanes_src, lanes_dst)
    k = np.asarray(k)
    v = np.asarray(v)
    np.testing.assert_array_equal(k[:, dst_block], k[:, src_block])
    np.testing.assert_array_equal(v[:, dst_block], v[:, src_block])
