"""ray_tpu.llm: paged attention parity, block pool, continuous batching.

Coverage demanded by the subsystem's acceptance criteria:

* paged single-position attention (Pallas interpret mode) == the XLA
  reference path to <= 2e-5;
* block-pool alloc / free / growth / preemption bookkeeping;
* the continuous-batching engine reproduces ``gptj_decode`` greedy
  token-for-token — including through admission waves, cancellation,
  stop tokens, deadlines, and recompute preemption under KV pressure;
* under staggered arrivals the engine beats sequential static-batch
  ``gptj_decode`` calls on aggregate tokens/s;
* a streamed serve client sees its first token before its completion
  finishes (TTFT < total latency) and the streamed tokens arrive in
  generation order.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.llm import CacheConfig, EngineConfig, KVBlockPool, LLMEngine, SamplingParams
from ray_tpu.models.gptj import GPTJConfig, gptj_decode, gptj_init

TINY = GPTJConfig(
    vocab_size=128, seq_len=64, d_model=32, n_layers=2, n_heads=2,
    rotary_dim=8, dtype="float32", remat=False, attn_impl="xla",
    fused_loss=False,
)


@pytest.fixture(scope="module")
def tiny_params():
    return gptj_init(jax.random.PRNGKey(0), TINY)


@pytest.fixture(scope="module")
def default_engine(tiny_params):
    """One engine shared by every test that uses the default geometry —
    each fresh engine re-jits its step functions, which dominates the
    file's runtime. Tests leave it drained (all requests finished)."""
    return _engine(tiny_params)


def _prompt(n, seed=1):
    return list(np.random.RandomState(seed).randint(0, TINY.vocab_size, n))


def _engine(params, **kw):
    defaults = dict(
        max_slots=3, num_blocks=32, block_size=4, max_blocks_per_seq=12,
        prefill_chunk=8,
    )
    defaults.update(kw)
    return LLMEngine(TINY, params, EngineConfig(**defaults))


def _drive(engine, reqs, timeout=120.0):
    deadline = time.monotonic() + timeout
    while not all(r.finished for r in reqs):
        engine.step()
        assert time.monotonic() < deadline, "engine did not finish in time"


def _ref_decode(params, prompt, n_new):
    out = gptj_decode(TINY, params, jnp.asarray([prompt], jnp.int32), n_new)
    return [int(t) for t in np.asarray(out)[0, len(prompt):]]


# ---------------------------------------------------------------------------
# paged attention op
# ---------------------------------------------------------------------------


class TestPagedAttention:
    def _case(self, seed=0, slots=3, heads=4, d=16, blocks=12, bs=4, tmax=6):
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(slots, heads, d), jnp.float32)
        kp = jnp.asarray(rng.randn(blocks, heads, bs, d), jnp.float32)
        vp = jnp.asarray(rng.randn(blocks, heads, bs, d), jnp.float32)
        bt = jnp.asarray(rng.randint(0, blocks, (slots, tmax)), jnp.int32)
        lens = jnp.asarray(rng.randint(1, tmax * bs + 1, slots), jnp.int32)
        return q, kp, vp, bt, lens

    def test_pallas_matches_xla(self):
        from ray_tpu.ops.paged_attention import paged_attention

        q, kp, vp, bt, lens = self._case()
        ref = paged_attention(q, kp, vp, bt, lens, impl="xla")
        out = paged_attention(q, kp, vp, bt, lens, impl="pallas")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_pallas_matches_xla_under_jit(self):
        from ray_tpu.ops.paged_attention import paged_attention

        q, kp, vp, bt, lens = self._case(seed=7)
        ref = paged_attention(q, kp, vp, bt, lens, impl="xla")
        out = jax.jit(lambda *a: paged_attention(*a, impl="pallas"))(
            q, kp, vp, bt, lens
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_xla_matches_dense_attend_cached(self):
        """The op generalizes gptj._attend_cached: gathering a slot's
        blocks into a dense cache and attending must agree."""
        from ray_tpu.models.gptj import _attend_cached
        from ray_tpu.ops.paged_attention import paged_attention

        q, kp, vp, bt, lens = self._case(seed=3)
        out = paged_attention(q, kp, vp, bt, lens, impl="xla")
        k = kp[bt].transpose(0, 2, 1, 3, 4).reshape(q.shape[0], q.shape[1], -1, q.shape[2])
        v = vp[bt].transpose(0, 2, 1, 3, 4).reshape(*k.shape)
        for s in range(q.shape[0]):
            dense = _attend_cached(
                q[s : s + 1], k[s : s + 1], v[s : s + 1], int(lens[s])
            )
            np.testing.assert_allclose(
                np.asarray(out[s]), np.asarray(dense[0]), atol=2e-5
            )

    def test_bad_impl_rejected(self):
        from ray_tpu.ops.paged_attention import paged_attention

        q, kp, vp, bt, lens = self._case()
        with pytest.raises(ValueError, match="unknown paged attention impl"):
            paged_attention(q, kp, vp, bt, lens, impl="cuda")


# ---------------------------------------------------------------------------
# block pool
# ---------------------------------------------------------------------------


class TestKVBlockPool:
    def _pool(self, num_blocks=9, bs=4, tmax=4):
        return KVBlockPool(
            CacheConfig(num_blocks, bs, tmax), n_layers=1, n_heads=1, head_dim=4
        )

    def test_alloc_free_roundtrip(self):
        pool = self._pool()
        assert pool.num_free_blocks == 8  # block 0 reserved
        blocks = pool.allocate("a", 10)  # ceil(10/4) = 3 blocks
        assert len(blocks) == 3 and 0 not in blocks
        assert pool.num_free_blocks == 5
        assert pool.utilization() == pytest.approx(3 / 8)
        row = pool.table_row("a")
        assert list(row[:3]) == blocks and list(row[3:]) == [0]
        assert pool.free("a") == 3
        assert pool.num_free_blocks == 8
        assert pool.free("a") == 0  # idempotent

    def test_grow_and_exhaustion(self):
        pool = self._pool(num_blocks=6, tmax=8)  # 5 usable
        pool.allocate("a", 4)       # 1 block
        pool.allocate("b", 16)      # 4 blocks -> pool dry
        assert not pool.can_allocate(1)
        assert pool.grow_to("a", 4) is True      # no growth needed
        assert pool.grow_to("a", 5) is False     # dry: growth refused
        pool.free("b")
        assert pool.grow_to("a", 5) is True
        assert len(pool.table_row("a").nonzero()[0]) == 2

    def test_alloc_errors(self):
        pool = self._pool(num_blocks=4, tmax=2)
        pool.allocate("a", 4)
        with pytest.raises(ValueError, match="already owns"):
            pool.allocate("a", 4)
        with pytest.raises(ValueError, match="max_blocks_per_seq"):
            pool.allocate("big", 100)
        pool.allocate("b", 8)
        with pytest.raises(MemoryError, match="exhausted"):
            pool.allocate("c", 4)
        with pytest.raises(KeyError):
            pool.table_row("ghost")


# ---------------------------------------------------------------------------
# engine: correctness vs gptj_decode
# ---------------------------------------------------------------------------


class TestEngine:
    def test_greedy_matches_gptj_decode(self, tiny_params, default_engine):
        eng = default_engine
        prompt = _prompt(10)
        out = eng.generate(prompt, SamplingParams(max_tokens=8))
        assert out == _ref_decode(tiny_params, prompt, 8)

    def test_concurrent_admission_matches_reference(self, tiny_params, default_engine):
        """Three requests of different prompt lengths decode together in
        one slot set; each must match its own single-request reference."""
        eng = default_engine
        prompts = [_prompt(5, seed=2), _prompt(9, seed=3), _prompt(13, seed=4)]
        reqs = [eng.submit(p, SamplingParams(max_tokens=10)) for p in prompts]
        _drive(eng, reqs)
        for req, p in zip(reqs, prompts):
            assert req.finish_reason == "length"
            assert req.out == _ref_decode(tiny_params, p, 10)
        # everything released
        s = eng.stats()
        assert s["running"] == 0 and s["kv_utilization"] == 0.0

    def test_preemption_under_pressure_matches_reference(self, tiny_params):
        """A pool too small for all three completions forces recompute
        preemption; outputs must still match the references exactly."""
        eng = _engine(
            tiny_params, max_slots=3, num_blocks=13, block_size=4,
            max_blocks_per_seq=10,
        )
        prompts = [_prompt(8, seed=s) for s in (5, 6, 7)]
        reqs = [eng.submit(p, SamplingParams(max_tokens=16)) for p in prompts]
        _drive(eng, reqs)
        assert eng.stats()["preemptions"] > 0, "pool was sized to force preemption"
        for req, p in zip(reqs, prompts):
            assert req.out == _ref_decode(tiny_params, p, 16)

    def test_queue_overflow_waits_then_runs(self, tiny_params):
        """More requests than slots: the overflow waits, then admits as
        slots free, FIFO."""
        eng = _engine(tiny_params, max_slots=2)
        prompts = [_prompt(6, seed=10 + i) for i in range(5)]
        reqs = [eng.submit(p, SamplingParams(max_tokens=6)) for p in prompts]
        assert eng.stats()["waiting"] >= 3  # only 2 slots
        _drive(eng, reqs)
        for req, p in zip(reqs, prompts):
            assert req.out == _ref_decode(tiny_params, p, 6)

    def test_stop_tokens(self, tiny_params, default_engine):
        prompt = _prompt(10)
        full = _ref_decode(tiny_params, prompt, 8)
        stop = full[3]
        eng = default_engine
        req = eng.submit(
            prompt, SamplingParams(max_tokens=8, stop_token_ids=(stop,))
        )
        _drive(eng, [req])
        assert req.finish_reason == "stop"
        cut = full.index(stop) + 1  # stop token included, HF-eos style
        assert req.out == full[:cut]

    def test_cancellation_frees_slot(self, tiny_params, default_engine):
        eng = default_engine
        req = eng.submit(_prompt(8), SamplingParams(max_tokens=30))
        for _ in range(6):
            eng.step()
        assert not req.finished and len(req.out) >= 1
        assert eng.cancel(req.id)
        eng.step()
        assert req.finished and req.finish_reason == "cancelled"
        s = eng.stats()
        assert s["running"] == 0 and s["kv_utilization"] == 0.0
        # the stream terminates too
        tokens = list(eng.stream_tokens(req, timeout=5.0))
        assert tokens == req.out
        assert eng.cancel("req-unknown") is False

    def test_deadline_reaps(self, tiny_params, default_engine):
        eng = default_engine
        # zero the observed service rate: with rate evidence the engine
        # would SHED this un-meetable deadline at submit (OverloadedError,
        # tests/test_llm_robustness.py); this test covers the reap path —
        # a request whose deadline blows after admission
        eng._rate = 0.0
        req = eng.submit(_prompt(8), SamplingParams(max_tokens=30), deadline_s=0.0)
        eng.step()
        assert req.finished and req.finish_reason == "deadline"

    def test_submit_validation(self, tiny_params, default_engine):
        eng = default_engine
        with pytest.raises(ValueError, match="max model length"):
            eng.submit(_prompt(40), SamplingParams(max_tokens=40))
        with pytest.raises(ValueError, match="max_tokens"):
            eng.submit(_prompt(4), SamplingParams(max_tokens=0))
        with pytest.raises(ValueError, match="prompt"):
            eng.submit([], SamplingParams(max_tokens=4))

    def test_oversized_request_rejected_not_livelocked(self, tiny_params):
        """A request that fits the model length but not the PHYSICAL pool
        must be rejected at submit — admitted, it could never be scheduled
        and would starve the FIFO head forever."""
        eng = _engine(tiny_params, num_blocks=5, max_blocks_per_seq=12)  # 4 usable
        with pytest.raises(ValueError, match="usable blocks"):
            eng.submit(_prompt(20), SamplingParams(max_tokens=10))
        # a request that does fit still works
        out = eng.generate(_prompt(6), SamplingParams(max_tokens=4))
        assert out == _ref_decode(tiny_params, _prompt(6), 4)

    def test_negative_seed_does_not_crash_engine(self, tiny_params, default_engine):
        """seed=-1 must not overflow the uint32 seed cell (NumPy >= 2
        raises OverflowError, which would kill the engine loop thread)."""
        eng = default_engine
        out = eng.generate(
            _prompt(6), SamplingParams(max_tokens=4, temperature=1.0, seed=-1)
        )
        assert len(out) == 4

    def test_sampled_decode_respects_temperature_and_seed(self, tiny_params, default_engine):
        """Sampling is deterministic per (seed, token-index) and actually
        diversifies across seeds."""
        eng = default_engine
        p = _prompt(8)
        sp = dict(max_tokens=12, temperature=1.5, top_k=0, top_p=1.0)
        a = eng.generate(p, SamplingParams(seed=1, **sp))
        b = eng.generate(p, SamplingParams(seed=1, **sp))
        c = eng.generate(p, SamplingParams(seed=2, **sp))
        assert a == b, "same seed must reproduce"
        assert a != c, "different seeds should diverge at temperature 1.5"
        assert all(0 <= t < TINY.vocab_size for t in a)


# ---------------------------------------------------------------------------
# sampling helper (shared by gptj_decode / gpt_decode / engine)
# ---------------------------------------------------------------------------


class TestSampling:
    def test_greedy_and_topk1_equal_argmax(self):
        from ray_tpu.models.sampling import sample_tokens

        logits = jnp.asarray(np.random.RandomState(0).randn(4, 50), jnp.float32)
        am = list(np.argmax(np.asarray(logits), -1))
        key = jax.random.PRNGKey(0)
        assert list(np.asarray(sample_tokens(logits, key, temperature=0.0))) == am
        assert (
            list(np.asarray(sample_tokens(logits, key, temperature=1.0, top_k=1)))
            == am
        )
        assert (
            list(np.asarray(sample_tokens(logits, key, temperature=1.0, top_p=1e-6)))
            == am
        )

    def test_topk_restricts_support(self):
        from ray_tpu.models.sampling import sample_tokens

        logits = jnp.asarray(np.random.RandomState(1).randn(2, 64), jnp.float32)
        top5 = np.argsort(-np.asarray(logits), -1)[:, :5]
        for i in range(20):
            toks = np.asarray(
                sample_tokens(logits, jax.random.PRNGKey(i), temperature=1.0, top_k=5)
            )
            for row in range(2):
                assert toks[row] in top5[row]

    def test_per_row_params(self):
        """Row 0 greedy, row 1 hot — one call, mixed params (the engine's
        decode batch mixes requests)."""
        from ray_tpu.models.sampling import sample_tokens

        logits = jnp.asarray(np.random.RandomState(2).randn(2, 32), jnp.float32)
        am = np.argmax(np.asarray(logits), -1)
        temps = jnp.asarray([0.0, 2.0])
        saw_diverge = False
        for i in range(20):
            toks = np.asarray(
                sample_tokens(logits, jax.random.PRNGKey(i), temperature=temps)
            )
            assert toks[0] == am[0]
            saw_diverge |= toks[1] != am[1]
        assert saw_diverge, "temperature-2.0 row never diverged from argmax"

    def test_gptj_decode_sampling_path(self, tiny_params):
        """gptj_decode with a key draws reproducibly and differs from
        greedy at high temperature."""
        prompt = jnp.asarray([_prompt(8)], jnp.int32)
        greedy = gptj_decode(TINY, tiny_params, prompt, 8)
        k = jax.random.PRNGKey(3)
        s1 = gptj_decode(TINY, tiny_params, prompt, 8, key=k, temperature=2.0)
        s2 = gptj_decode(TINY, tiny_params, prompt, 8, key=k, temperature=2.0)
        assert np.array_equal(np.asarray(s1), np.asarray(s2))
        assert not np.array_equal(np.asarray(s1), np.asarray(greedy))

    def test_gpt_decode_matches_forward_and_samples(self):
        """gpt_decode greedy continuation is argmax-consistent with
        gpt_forward, and the sampling path reproduces per key."""
        from ray_tpu.models.gpt import GPTConfig, gpt_decode, gpt_forward, gpt_init

        cfg = GPTConfig(
            vocab_size=96, seq_len=48, d_model=32, n_layers=2, n_heads=2,
            dtype="float32", remat=False, attn_impl="xla", fused_loss=False,
        )
        params = gpt_init(jax.random.PRNGKey(1), cfg)
        prompt = jnp.asarray([list(range(7, 17))], jnp.int32)
        out = gpt_decode(cfg, params, prompt, 5)
        # step-by-step argmax over the full forward == cached decode
        seq = list(np.asarray(prompt)[0])
        for _ in range(5):
            logits = gpt_forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(np.argmax(np.asarray(logits)[0, -1])))
        assert list(np.asarray(out)[0]) == seq
        k = jax.random.PRNGKey(5)
        s1 = gpt_decode(cfg, params, prompt, 5, key=k, temperature=1.5)
        s2 = gpt_decode(cfg, params, prompt, 5, key=k, temperature=1.5)
        assert np.array_equal(np.asarray(s1), np.asarray(s2))


# ---------------------------------------------------------------------------
# throughput: continuous vs sequential static batching (acceptance)
# ---------------------------------------------------------------------------


def test_continuous_beats_sequential_static_batching():
    """Staggered arrivals, identical greedy workload: the engine's
    aggregate tokens/s must be STRICTLY higher than sequential
    static-batch gptj_decode calls (ray_tpu/llm/bench.py, which also
    asserts token-level equality of the two paths)."""
    from ray_tpu.llm.bench import run_bench

    rec = run_bench()
    cont = rec["value"]
    static = rec["detail"]["static_tokens_per_sec"]
    assert cont > static, (
        f"continuous batching ({cont} tok/s) did not beat sequential "
        f"static batching ({static} tok/s)"
    )


# ---------------------------------------------------------------------------
# serve integration: streaming through a deployment replica
# ---------------------------------------------------------------------------


@pytest.fixture
def serve_instance():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_llm_deployment_streams_tokens(serve_instance, tiny_params):
    """End-to-end through the serve stack: deploy, stream a completion,
    check TTFT < total latency, ordering, and the autoscaling signals."""
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_app

    app = build_llm_app(
        model="gptj",
        model_cfg=TINY,
        engine_config=EngineConfig(
            max_slots=2, num_blocks=32, block_size=4, max_blocks_per_seq=12,
            prefill_chunk=8,
        ),
    )
    handle = serve.run(app, name="llm")
    prompt = _prompt(10)
    n_new = 24

    t0 = time.monotonic()
    ttft = None
    streamed = []
    for tok in handle.options(stream=True).remote(prompt, max_tokens=n_new):
        if ttft is None:
            ttft = time.monotonic() - t0
        streamed.append(tok)
    total = time.monotonic() - t0

    # acceptance: a streamed client observes its first token before the
    # completion finishes
    assert ttft is not None and ttft < total, (ttft, total)
    assert len(streamed) == n_new
    # ordering: the stream IS the generation order — it must equal the
    # reference decode, token for token
    assert streamed == _ref_decode(tiny_params, prompt, n_new)

    # non-streaming method path agrees
    blocking = handle.generate.remote(prompt, max_tokens=n_new).result(timeout=60)
    assert blocking == streamed

    # autoscaling signal surface
    m = handle.autoscaling_metrics.remote().result(timeout=30)
    assert set(m) >= {"queue_depth", "kv_utilization", "running", "waiting"}
    assert m["running"] == 0 and m["queue_depth"] == 0


def test_batch_queue_exports_saturation_metrics(serve_instance):
    """@serve.batch queues expose depth + last-flush size (the signal
    surface replica autoscaling reads)."""
    import threading

    from ray_tpu import serve
    from ray_tpu.serve.batching import _BatchQueue

    class Model:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
        def predict(self, xs):
            time.sleep(0.02)
            return [x * 2 for x in xs]

    m = Model()
    results = []
    threads = [
        threading.Thread(target=lambda i=i: results.append(m.predict(i)))
        for i in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(results) == [0, 2, 4, 6]
    q = getattr(m, "__serve_batch_queues_predict")[""]
    assert isinstance(q, _BatchQueue)
    assert q.last_flush_size >= 1
    assert q.queue_depth() == 0
    from ray_tpu.util.metrics import collect

    data = collect()
    assert "serve_batch_queue_depth" in data["metrics"]
    assert "serve_batch_last_flush_size" in data["metrics"]
