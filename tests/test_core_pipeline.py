"""Pipelined submission & batched reply plane (ISSUE 14).

* **FIFO matrix**: execution order equals submission order across every
  batching seam — driver dispatch coalescing (``run_task_batch``),
  worker-side submit windows (``submit_batch``), reply coalescing
  (``tasks_done_batch``), and interleaved actor+task bursts (each
  stream's own FIFO holds; no contract spans streams).
* **Async error surfacing**: submission is fire-and-forget, so
  submit-time failures (dead actor, oversized inline spec) resolve on
  the RETURN refs — the ``.remote()`` call site never raises.
* **Waterfall integrity**: sampled tasks that rode batched legs still
  fold all 7 legs (8 stamps) with monotonic timestamps — batching moves
  WHERE a stamp is taken, never whether.
* **Batch telemetry**: ``core_submit_batch_size`` sees real windows and
  the ``obs top`` row honors the below-2-samples ``—`` contract.
* **Chaos**: the head socket dying mid-burst resolves EVERY in-flight
  ref to a result or a retriable error — never a hang (fail-not-replay
  is the pinned semantic for un-acked submit windows: a blind replay of
  a window the head DID process would double-submit its tasks).
"""

import os
import subprocess
import sys
import threading
import time

import pytest

import ray_tpu
from ray_tpu import exceptions as rex
from ray_tpu.util import metrics as um
from ray_tpu.util import tracing
from ray_tpu.util import waterfall as wfl


def _hist(name: str) -> dict:
    """First (sole) series of a histogram's percentile snapshot."""
    for v in um.histogram_percentiles(name).get(name, {}).values():
        return v
    return {"count": 0, "sum": 0.0}


@ray_tpu.remote
class Recorder:
    def __init__(self):
        self.order = []

    def add(self, i):
        self.order.append(i)

    def snapshot(self):
        return list(self.order)


# ---------------------------------------------------------------------------
# FIFO ordering across batch seams
# ---------------------------------------------------------------------------


class TestFifoUnderBatching:
    def test_actor_burst_preserves_submission_order(self, ray_start_regular):
        """A driver-side burst of actor calls (no gets in between) rides
        coalesced run_task_batch dispatches; per-actor FIFO must hold."""
        r = Recorder.remote()
        refs = [r.add.remote(i) for i in range(200)]
        ray_tpu.get(refs, timeout=120)
        assert ray_tpu.get(r.snapshot.remote(), timeout=60) == list(range(200))

    def test_worker_submit_window_preserves_actor_fifo(self, ray_start_regular):
        """A WORKER fan-out rides the pipelined submit_batch path (window
        flow control + header split); the head processes each window in
        submission order, so per-actor FIFO survives the batching."""
        r = Recorder.remote()

        @ray_tpu.remote
        def fan(rec, n):
            got = [rec.add.remote(i) for i in range(n)]
            ray_tpu.get(got)
            return n

        base = _hist("core_submit_batch_size")
        assert ray_tpu.get(fan.remote(r, 128), timeout=120) == 128
        assert ray_tpu.get(r.snapshot.remote(), timeout=60) == list(range(128))
        # the burst really rode submit windows: the head observed them
        after = _hist("core_submit_batch_size")
        assert after["count"] > base["count"]
        # and the window sizes sum to (at least) the burst's tasks
        assert after["sum"] - base["sum"] >= 128

    def test_single_worker_lease_chain_fifo(self, tmp_path):
        """One CPU slot = one worker: a task burst drains through lease
        chains and coalesced dispatch batches in strict submission
        order (append-only file records execution order)."""
        ray_tpu.init(num_cpus=1, num_tpus=0)
        try:
            path = str(tmp_path / "order.txt")

            @ray_tpu.remote
            def mark(p, i):
                with open(p, "a") as f:
                    f.write(f"{i}\n")
                return i

            refs = [mark.remote(path, i) for i in range(100)]
            assert ray_tpu.get(refs, timeout=120) == list(range(100))
            with open(path) as f:
                seen = [int(line) for line in f]
            assert seen == list(range(100))
        finally:
            ray_tpu.shutdown()

    def test_interleaved_actor_and_task_bursts(self, tmp_path):
        """Interleaved actor calls and plain tasks: each stream keeps its
        OWN FIFO (per-actor, per-worker) across shared batch messages."""
        ray_tpu.init(num_cpus=1, num_tpus=0)
        try:
            path = str(tmp_path / "order.txt")
            r = Recorder.remote()

            @ray_tpu.remote
            def mark(p, i):
                with open(p, "a") as f:
                    f.write(f"{i}\n")

            refs = []
            for i in range(60):
                refs.append(r.add.remote(i))
                refs.append(mark.remote(path, i))
            ray_tpu.get(refs, timeout=120)
            assert ray_tpu.get(r.snapshot.remote(), timeout=60) == list(range(60))
            with open(path) as f:
                assert [int(line) for line in f] == list(range(60))
        finally:
            ray_tpu.shutdown()


class TestHeaderSplit:
    def test_streaming_actor_method_mints_header(self, ray_start_regular):
        """num_returns='streaming' actor calls ride the header-split path
        too — the content-derived id must accept the STRING (a %d format
        crashed exactly here once) and the stream must work end to end."""

        @ray_tpu.remote
        class Gen:
            @ray_tpu.method(num_returns="streaming")
            def count(self, n):
                for i in range(n):
                    yield i

        g = Gen.remote()
        got = [ray_tpu.get(r) for r in g.count.remote(4)]
        assert got == [0, 1, 2, 3]
        # twice: the second call rides the cached header reference
        got = [ray_tpu.get(r) for r in g.count.remote(3)]
        assert got == [0, 1, 2]

    def test_header_ids_stable_across_handle_copies(self, ray_start_regular):
        """Deserialized handle copies must mint the SAME header id for the
        same method (content-derived, not per-instance random) — receiver
        caches dedupe instead of growing one entry per copy."""
        r = Recorder.remote()
        ray_tpu.get(r.add.remote(0), timeout=60)
        hid1 = r._hdr_cache[("add", 1)][0]
        import pickle as _pickle

        r2 = _pickle.loads(_pickle.dumps(r))
        ray_tpu.get(r2.add.remote(1), timeout=60)
        assert r2._hdr_cache[("add", 1)][0] == hid1


# ---------------------------------------------------------------------------
# async submit-error surfacing on refs
# ---------------------------------------------------------------------------


class TestAsyncSubmitErrors:
    def test_dead_actor_surfaces_on_ref(self, ray_start_regular):
        """Calling a dead actor must not raise at the .remote() call site
        (submission is fire-and-forget); the error resolves on the ref."""
        r = Recorder.remote()
        ray_tpu.get(r.add.remote(0), timeout=60)
        ray_tpu.kill(r)
        ref = r.add.remote(1)  # call site must NOT raise
        with pytest.raises(rex.RayActorError):
            ray_tpu.get(ref, timeout=60)

    def test_dead_actor_surfaces_on_ref_from_worker(self, ray_start_regular):
        """Same contract through the socket submit_batch path: a worker's
        window item for a dead actor fails that ITEM's refs — the window
        itself completes and is acked (credits can never wedge)."""
        r = Recorder.remote()
        ray_tpu.get(r.add.remote(0), timeout=60)
        ray_tpu.kill(r)

        @ray_tpu.remote
        def poke(rec):
            ref = rec.add.remote(1)  # must not raise here either
            try:
                ray_tpu.get(ref, timeout=30)
                return "no-error"
            except rex.RayActorError:
                return "actor-error"

        assert ray_tpu.get(poke.remote(r), timeout=120) == "actor-error"

    def test_oversized_inline_spec_fails_on_ref(self, ray_start_regular, monkeypatch):
        """A window item whose inline (by-value) argument bytes exceed
        core_max_spec_inline_bytes resolves its refs to a ValueError that
        says to put() the argument — asynchronously, without poisoning
        the rest of the window."""
        from ray_tpu._private.config import GLOBAL_CONFIG

        monkeypatch.setattr(GLOBAL_CONFIG, "core_max_spec_inline_bytes", 4096)

        @ray_tpu.remote
        def fan_big():
            @ray_tpu.remote
            def eat(b):
                return len(b)

            # 32KB stays under the auto-put threshold, so it ships inline
            # in the submit window and trips the head-side cap
            big = eat.remote(b"x" * 32768)
            ok = eat.remote(b"y" * 16)  # same window, small: must succeed
            assert ray_tpu.get(ok, timeout=30) == 16
            try:
                ray_tpu.get(big, timeout=30)
                return "no-error"
            except Exception as e:  # noqa: BLE001 - asserting the message
                return f"error:{e}"

        out = ray_tpu.get(fan_big.remote(), timeout=120)
        assert out.startswith("error:") and "put()" in out


# ---------------------------------------------------------------------------
# waterfall integrity under batching
# ---------------------------------------------------------------------------


class TestWaterfallUnderBatching:
    def test_batched_tasks_fold_all_phases_monotonic(self, ray_start_regular):
        """Sampled tasks that rode submit windows, coalesced dispatches,
        and reply batches still fold ALL 7 legs with monotonic stamps —
        no phase is silently dropped by batching."""
        wfl.clear()
        from ray_tpu._private.runtime import get_ctx

        @ray_tpu.remote
        def leaf(i):
            return i

        @ray_tpu.remote
        def fan(n):
            return sum(ray_tpu.get([leaf.remote(i) for i in range(n)]))

        before = get_ctx().call("waterfall")["folded"]
        with tracing.trace_context() as rid:
            assert ray_tpu.get(fan.remote(32), timeout=120) == sum(range(32))
        s = get_ctx().call("waterfall", recent=64)
        assert s["folded"] - before == 33  # 32 batched leaves + the parent
        assert s["incomplete"] == 0
        ours = [rec for rec in s["recent"] if rec.get("request_id") == rid]
        assert len(ours) >= 33
        for rec in ours:
            stamps = rec["stamps"]
            assert len(stamps) == len(wfl.PHASES)
            assert stamps == sorted(stamps), (
                f"non-monotone stamps for {rec.get('name')}: {stamps}"
            )
            assert all(v >= 0 for v in rec["legs"].values())


# ---------------------------------------------------------------------------
# batch telemetry
# ---------------------------------------------------------------------------


class TestBatchTelemetry:
    def test_reply_batches_observed(self, ray_start_regular):
        """A burst of short actor calls coalesces completions into
        tasks_done_batch messages; the head's size histogram sees them.
        Coalescing is load-dependent (the off-path flusher drains
        whatever accumulated), so drive bursts until one lands."""
        base = _hist("core_reply_batch_size")
        r = Recorder.remote()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            ray_tpu.get([r.add.remote(i) for i in range(256)], timeout=120)
            if _hist("core_reply_batch_size")["count"] > base["count"]:
                return
        pytest.fail("no coalesced reply batch observed after repeated bursts")

    def test_core_batch_top_row_contract(self):
        """obs top's core-batch row: absent without the metrics, and a
        histogram below 2 samples renders the `—` placeholder."""
        from ray_tpu.obs import core_batch_top_row

        assert core_batch_top_row({}, {}) is None
        metrics = {
            "core_submit_batch_size": {"": 1.0},
            "core_submit_credits": {"": 4096.0},
        }
        pcts = {"core_submit_batch_size": {"": {"count": 1, "p50": 1.0, "p99": 1.0}}}
        row = core_batch_top_row(metrics, pcts)
        assert row is not None
        assert "submit=—" in row and "reply=—" in row
        assert "credits=4096" in row
        pcts = {
            "core_submit_batch_size": {"": {"count": 9, "p50": 8.0, "p99": 32.0}},
            "core_reply_batch_size": {"": {"count": 4, "p50": 2.0, "p99": 4.0}},
        }
        row = core_batch_top_row(metrics, pcts)
        assert "submit=8/32" in row and "reply=2/4" in row


# ---------------------------------------------------------------------------
# chaos: head socket death mid-burst
# ---------------------------------------------------------------------------

HEAD_SCRIPT = (
    "import ray_tpu, time;"
    "info = ray_tpu.init(num_cpus=2);"
    "from ray_tpu._private.runtime import get_ctx;"
    "head = get_ctx().head;"
    "h, p = head.listen_tcp('127.0.0.1', 0);"
    "print(f'ADDR {h}:{p}', flush=True);"
    "time.sleep(180)"
)


@pytest.fixture
def tcp_head():
    key = os.urandom(16).hex()
    env = dict(
        os.environ,
        RAY_TPU_AUTHKEY=key,
        RAY_TPU_CLIENT_RECONNECT_GRACE_S="5",
        RAY_TPU_HEALTH_CHECK_INTERVAL_S="0.2",
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", HEAD_SCRIPT], stdout=subprocess.PIPE, text=True, env=env
    )
    os.environ["RAY_TPU_AUTHKEY"] = key
    line = proc.stdout.readline()
    assert line.startswith("ADDR"), line
    addr = line.split()[1]
    try:
        yield addr
    finally:
        os.environ.pop("RAY_TPU_AUTHKEY", None)
        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
        proc.terminate()
        proc.wait(timeout=10)


class TestChaosMidBurst:
    def test_socket_death_mid_burst_resolves_every_ref(self, tcp_head):
        """Kill the driver↔head socket while a submit burst is in flight:
        every ref must resolve — a result (the head processed its window
        before the cut, or after the token redial) or a retriable error
        (un-acked window / unsent buffer, failed not replayed) — and
        NEVER hang. The plane must keep working after the redial."""
        ray_tpu.init(address=f"ray://{tcp_head}")
        try:
            from ray_tpu._private.node_agent import shutdown_conn
            from ray_tpu._private.runtime import get_ctx

            @ray_tpu.remote
            def f(i):
                return i

            ctx = get_ctx()
            refs = []

            def burst():
                for i in range(400):
                    refs.append(f.remote(i))

            t = threading.Thread(target=burst)
            t.start()
            while len(refs) < 50:  # let real windows get in flight first
                time.sleep(0.001)
            shutdown_conn(ctx.conn)  # violent drop, no goodbye
            t.join(timeout=120)
            assert not t.is_alive(), "submitter wedged after socket death"
            assert len(refs) == 400

            deadline = time.monotonic() + 90
            ok = failed = 0
            for i, ref in enumerate(refs):
                while True:
                    try:
                        assert ray_tpu.get(ref, timeout=60) == i
                        ok += 1
                        break
                    except rex.GetTimeoutError:
                        pytest.fail(f"ref {i} hung after mid-burst socket death")
                    except rex.RayError as e:
                        if "while sending" in str(e) and time.monotonic() < deadline:
                            # transient send-into-dying-socket error during
                            # the redial window — the pinned contract says
                            # retry, so the test does
                            time.sleep(0.2)
                            continue
                        failed += 1
                        break
            assert ok + failed == 400
            # a poisoned (failed-submit) ref counts READY for wait():
            # waiters drain instead of spinning on ids the head never saw
            while True:
                try:
                    _ready, not_ready = ray_tpu.wait(
                        refs, num_returns=len(refs), timeout=30
                    )
                    break
                except rex.RayError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.3)
            assert not not_ready

            # the plane recovered: fresh tasks run on the same session
            while True:
                try:
                    assert ray_tpu.get(f.remote(12345), timeout=60) == 12345
                    break
                except rex.GetTimeoutError:
                    pytest.fail("post-recovery task hung")
                except rex.RayError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.3)
        finally:
            ray_tpu.shutdown()

    def test_socket_death_mid_put_burst_replays_every_put(self, tcp_head):
        """Puts are the at-most-once EXCEPTION: a put id is minted exactly
        once per op, so un-acked/unsent puts at a socket drop are REPLAYED
        on the fresh conn (head dedupes replay-flagged redelivery) instead
        of poisoned like tasks. Every ref must resolve to its VALUE — not
        a retriable error — once the redial lands."""
        ray_tpu.init(address=f"ray://{tcp_head}")
        try:
            from ray_tpu._private.node_agent import shutdown_conn
            from ray_tpu._private.runtime import get_ctx

            ctx = get_ctx()
            refs = []

            def burst():
                for i in range(200):
                    refs.append(ray_tpu.put({"i": i}))

            t = threading.Thread(target=burst)
            t.start()
            while len(refs) < 25:  # let real windows get in flight first
                time.sleep(0.001)
            shutdown_conn(ctx.conn)  # violent drop, no goodbye
            t.join(timeout=120)
            assert not t.is_alive(), "putter wedged after socket death"
            assert len(refs) == 200

            deadline = time.monotonic() + 90
            for i, ref in enumerate(refs):
                while True:
                    try:
                        assert ray_tpu.get(ref, timeout=60) == {"i": i}
                        break
                    except rex.GetTimeoutError:
                        pytest.fail(f"put {i} hung after mid-burst socket death")
                    except rex.RayError as e:
                        # transient send-into-dying-socket errors during the
                        # redial window retry; a POISONED put would repeat
                        # forever and trip the deadline — that's the failure
                        # this test exists to catch
                        if time.monotonic() > deadline:
                            pytest.fail(f"put {i} never resolved to its value: {e}")
                        time.sleep(0.2)
        finally:
            ray_tpu.shutdown()
