"""Autoscaler v2: per-instance lifecycle FSM + reconciler (reference:
python/ray/autoscaler/v2/instance_manager — validated transitions, status
history, cloud<->ray-node pairing, allocation retries with backoff)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler.v2 import (
    ALLOCATED,
    ALLOCATION_FAILED,
    QUEUED,
    RAY_RUNNING,
    REQUESTED,
    TERMINATED,
    TERMINATING,
    AutoscalerV2,
    FakeAsyncProvider,
    Instance,
)


def test_fsm_rejects_invalid_transitions():
    inst = Instance("t")
    inst.set_status(REQUESTED)
    with pytest.raises(ValueError, match="invalid transition"):
        inst.set_status(RAY_RUNNING)  # must pass through ALLOCATED
    inst.set_status(ALLOCATED)
    inst.set_status(RAY_RUNNING)
    inst.set_status(TERMINATING)
    inst.set_status(TERMINATED)
    with pytest.raises(ValueError):
        inst.set_status(QUEUED)  # terminal
    assert [s for s, _t in inst.status_history] == [
        QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING, TERMINATING, TERMINATED,
    ]


def test_scale_up_full_lifecycle(ray_start_regular):
    """Unplaceable demand drives QUEUED→REQUESTED→ALLOCATED→RAY_RUNNING,
    and the task then actually schedules on the joined node."""
    from ray_tpu._private.runtime import get_ctx

    head = get_ctx().head

    @ray_tpu.remote(resources={"bignode": 1.0})
    def needs_big():
        return "ran"

    ref = needs_big.remote()  # infeasible until the autoscaler acts
    provider = FakeAsyncProvider(cluster=head, delay_polls=2)
    asv2 = AutoscalerV2(
        provider,
        {"big": {"resources": {"CPU": 4.0, "bignode": 4.0}, "max_workers": 2}},
        head=head,
    )
    statuses = []
    for _ in range(8):
        counts = asv2.update()
        statuses.append(dict(counts))
        if counts.get(RAY_RUNNING):
            break
        time.sleep(0.05)
    assert any(s.get(REQUESTED) for s in statuses), statuses  # passed through
    assert statuses[-1].get(RAY_RUNNING) == 1, statuses
    assert ray_tpu.get(ref, timeout=60) == "ran"
    inst = next(iter(asv2.im.instances.values()))
    assert inst.ray_node_id and inst.provider_id in provider.created


def test_allocation_failure_retries_with_backoff(ray_start_regular):
    from ray_tpu._private.runtime import get_ctx

    head = get_ctx().head
    provider = FakeAsyncProvider(cluster=head, delay_polls=1, fail_first=2)
    asv2 = AutoscalerV2(
        provider,
        {"w": {"resources": {"CPU": 1.0, "w": 1.0}, "min_workers": 1, "max_workers": 1}},
        head=head,
        retry_backoff_s=0.05,
    )
    deadline = time.monotonic() + 20
    saw_failed = False
    while time.monotonic() < deadline:
        counts = asv2.update()
        saw_failed = saw_failed or bool(counts.get(ALLOCATION_FAILED))
        if counts.get(RAY_RUNNING):
            break
        time.sleep(0.06)
    assert saw_failed, "failure injection never observed"
    inst = next(iter(asv2.im.instances.values()))
    assert inst.status == RAY_RUNNING and inst.retries == 2


def test_retry_budget_exhaustion(ray_start_regular):
    from ray_tpu._private.runtime import get_ctx

    head = get_ctx().head
    provider = FakeAsyncProvider(cluster=head, delay_polls=1, fail_first=99)
    asv2 = AutoscalerV2(
        provider,
        {"w": {"resources": {"CPU": 1.0}, "min_workers": 1, "max_workers": 1}},
        head=head,
        max_allocation_retries=2,
        retry_backoff_s=0.01,
    )
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        counts = asv2.update()
        insts = [
            i for i in asv2.im.instances.values()
            if i.status == TERMINATED and i.retries > 2
        ]
        if insts:
            break
        time.sleep(0.02)
    assert insts, "instance never gave up"


# tier-1 budget (ISSUE 13): 10.5s measured on the dev box (real idle
# timers have to elapse); the remaining v2 suite keeps scale-up/down
# policy coverage in tier-1
@pytest.mark.slow
def test_idle_scale_down_respects_min_workers(ray_start_regular):
    from ray_tpu._private.runtime import get_ctx

    head = get_ctx().head
    provider = FakeAsyncProvider(cluster=head, delay_polls=1)
    asv2 = AutoscalerV2(
        provider,
        {"w": {"resources": {"CPU": 1.0, "scaletest": 1.0}, "min_workers": 2, "max_workers": 4}},
        head=head,
        idle_timeout_s=0.2,
    )
    # reach 2 RAY_RUNNING (min_workers), then add demand-driven extras
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        counts = asv2.update()
        if counts.get(RAY_RUNNING, 0) >= 2:
            break
        time.sleep(0.05)
    assert counts.get(RAY_RUNNING, 0) == 2
    # idle nodes past timeout: min_workers floor must hold
    time.sleep(0.4)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        counts = asv2.update()
        time.sleep(0.05)
    running = asv2.im.with_status(RAY_RUNNING)
    assert len(running) == 2, counts  # floor held, nothing below min


def test_labeled_demand_launches_labeled_node(ray_start_regular):
    """A hard-label task must (a) pick the node type whose labels satisfy it
    and (b) actually run — i.e. the provider stamps the type's labels onto
    the launched node, not just instance_id."""
    from ray_tpu._private.runtime import get_ctx
    from ray_tpu.util.scheduling_strategies import NodeLabelSchedulingStrategy

    head = get_ctx().head

    @ray_tpu.remote(scheduling_strategy=NodeLabelSchedulingStrategy(
        hard={"accel": "v5e"}), resources={"labnode": 1.0})
    def on_v5e():
        return "labeled"

    ref = on_v5e.remote()  # no node carries accel=v5e yet
    provider = FakeAsyncProvider(cluster=head, delay_polls=1)
    asv2 = AutoscalerV2(
        provider,
        {
            "plain": {"resources": {"CPU": 4.0, "labnode": 4.0}, "max_workers": 2},
            "lab": {"resources": {"CPU": 4.0, "labnode": 4.0},
                    "labels": {"accel": "v5e"}, "max_workers": 2},
        },
        head=head,
    )
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        counts = asv2.update()
        if counts.get(RAY_RUNNING):
            break
        time.sleep(0.05)
    # the plain type also fits the resource shape, but only 'lab' satisfies
    # the hard label — exactly one instance, of the labeled type
    types = [i.node_type for i in asv2.im.active()]
    assert types == ["lab"], types
    assert ray_tpu.get(ref, timeout=60) == "labeled"
