"""Serve library tests.

Mirrors the reference's ``python/ray/serve/tests`` coverage themes: deploy +
handle calls, replica scaling, composition, batching, autoscaling, HTTP
ingress, replica fault tolerance, and serving a jitted JAX model.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_instance():
    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_deploy_function_and_class(serve_instance):
    @serve.deployment
    def square(x):
        return x * x

    @serve.deployment
    class Counter:
        def __init__(self):
            self.n = 0

        def __call__(self, inc):
            self.n += inc
            return self.n

    h = serve.run(square.bind(), name="fn")
    assert h.remote(7).result(timeout=30) == 49

    h2 = serve.run(Counter.bind(), name="cls")
    assert h2.remote(2).result(timeout=30) == 2
    assert h2.remote(3).result(timeout=30) == 5


def test_replicas_share_load(serve_instance):
    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class WhoAmI:
        def __init__(self):
            import os

            self.pid = os.getpid()

        def __call__(self, _):
            time.sleep(0.05)
            return self.pid

    h = serve.run(WhoAmI.bind(), name="who")
    # drive concurrent request waves until both replicas have served
    # (a replica can lag through a startup health-check; pow-2 routing must
    # spread load across both once live)
    seen = set()
    lock = threading.Lock()

    def call(i):
        r = h.remote(i).result(timeout=60)
        with lock:
            seen.add(r)

    deadline = time.time() + 30
    while len(seen) < 2 and time.time() < deadline:
        threads = [threading.Thread(target=call, args=(i,)) for i in range(20)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert len(seen) == 2, f"expected 2 replica pids, saw {seen}"


def test_composition_chain(serve_instance):
    @serve.deployment
    class Tokenizer:
        def __call__(self, text):
            return text.split()

    @serve.deployment
    class Len:
        def __init__(self, tok):
            self.tok = tok

        def __call__(self, text):
            return len(self.tok.remote(text).result())

    h = serve.run(Len.bind(Tokenizer.bind()), name="chain")
    assert h.remote("a b c d").result(timeout=30) == 4


def test_batching_coalesces(serve_instance):
    @serve.deployment(max_ongoing_requests=16)
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
        def predict(self, xs):
            self.batch_sizes.append(len(xs))
            return [x + 1 for x in xs]

        def __call__(self, x):
            return self.predict(x)

        def sizes(self):
            return self.batch_sizes

    h = serve.run(Batched.bind(), name="batch")
    outs = []
    lock = threading.Lock()

    def call(i):
        r = h.remote(i).result(timeout=60)
        with lock:
            outs.append((i, r))

    threads = [threading.Thread(target=call, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(outs) == [(i, i + 1) for i in range(16)]
    sizes = h.sizes.remote().result(timeout=30)
    assert max(sizes) > 1, f"batching never coalesced: {sizes}"


# tier-1 budget (ISSUE 20): 8.1s measured (real autoscaler timers have to
# elapse) — rides slow; tests/test_autoscaler_v2.py keeps scale-up/down
# policy coverage in tier-1
@pytest.mark.slow
def test_autoscaling_up_and_down(serve_instance):
    @serve.deployment(
        max_ongoing_requests=2,
        autoscaling_config=dict(
            min_replicas=1,
            max_replicas=3,
            target_ongoing_requests=1,
            upscale_delay_s=0.2,
            downscale_delay_s=0.5,
        ),
    )
    class Slow:
        def __call__(self, _):
            time.sleep(0.4)
            return 1

    h = serve.run(Slow.bind(), name="auto")
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    dep = "auto_Slow"
    assert ray_tpu.get(controller.get_deployment_status.remote(dep), timeout=30)[
        "running_replicas"
    ] == 1

    stop = time.time() + 6.0
    threads = []

    def hammer():
        while time.time() < stop:
            try:
                h.remote(0).result(timeout=30)
            except Exception:
                return

    for _ in range(6):
        t = threading.Thread(target=hammer)
        t.start()
        threads.append(t)
    # must scale beyond 1 under sustained pressure
    scaled_up = False
    while time.time() < stop:
        st = ray_tpu.get(controller.get_deployment_status.remote(dep), timeout=30)
        if st["running_replicas"] > 1:
            scaled_up = True
            break
        time.sleep(0.2)
    for t in threads:
        t.join()
    assert scaled_up, "never scaled above 1 replica under load"
    # idle: must come back down to min_replicas
    deadline = time.time() + 15
    while time.time() < deadline:
        st = ray_tpu.get(controller.get_deployment_status.remote(dep), timeout=30)
        if st["target_replicas"] == 1:
            break
        time.sleep(0.3)
    assert st["target_replicas"] == 1, f"never scaled down: {st}"


def test_http_ingress(serve_instance):
    @serve.deployment
    class Echo:
        def __call__(self, payload):
            return {"got": payload, "ok": True}

    serve.run(Echo.bind(), name="web", http=True, http_port=0)
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    port = ray_tpu.get(controller.get_proxy_port.remote(), timeout=30)
    assert port

    def post(i):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/web",
            data=json.dumps({"i": i}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())

    out = post(1)
    assert out == {"got": {"i": 1}, "ok": True}
    # 100 concurrent HTTP requests
    results = []
    lock = threading.Lock()

    def worker(i):
        r = post(i)
        with lock:
            results.append(r["got"]["i"])

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(100)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(results) == list(range(100))


def test_replica_death_recovery(serve_instance):
    @serve.deployment(num_replicas=2)
    class Sturdy:
        def __call__(self, x):
            return x + 1

    h = serve.run(Sturdy.bind(), name="sturdy")
    assert h.remote(1).result(timeout=30) == 2
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    _, replicas, _cap = ray_tpu.get(
        controller.get_replicas.remote("sturdy_Sturdy"), timeout=30
    )
    ray_tpu.kill(replicas[0])
    # requests keep succeeding (retry/re-route), and the pool heals
    for i in range(10):
        assert h.remote(i).result(timeout=60) == i + 1
    deadline = time.time() + 20
    while time.time() < deadline:
        st = ray_tpu.get(
            controller.get_deployment_status.remote("sturdy_Sturdy"), timeout=30
        )
        if st["running_replicas"] == 2:
            break
        time.sleep(0.25)
    assert st["running_replicas"] == 2


def test_serve_jax_model(serve_instance):
    """Deploy a jitted JAX model behind @serve.batch — the TPU-inference
    shape: concurrent single requests coalesce into one batched forward."""

    @serve.deployment(max_ongoing_requests=16)
    class MLP:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            k1, k2 = jax.random.split(jax.random.PRNGKey(0))
            self.w1 = jax.random.normal(k1, (4, 32))
            self.w2 = jax.random.normal(k2, (32, 2))
            self._fwd = jax.jit(lambda x: jnp.argmax(jnp.tanh(x @ self.w1) @ self.w2, -1))

        @serve.batch(max_batch_size=16, batch_wait_timeout_s=0.02)
        def predict(self, xs):
            import numpy as np

            batch = np.stack(xs)
            return [int(v) for v in np.asarray(self._fwd(batch))]

        def __call__(self, x):
            return self.predict(np.asarray(x, np.float32))

    h = serve.run(MLP.bind(), name="mlp")
    xs = [np.random.default_rng(i).normal(size=4).astype(np.float32) for i in range(12)]
    results = [None] * 12
    threads = [
        threading.Thread(target=lambda i=i: results.__setitem__(i, h.remote(xs[i].tolist()).result(timeout=60)))
        for i in range(12)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r in (0, 1) for r in results)


def test_deployment_options_and_user_config(serve_instance):
    @serve.deployment
    class Tunable:
        def __init__(self):
            self.factor = 1

        def reconfigure(self, cfg):
            self.factor = cfg["factor"]

        def __call__(self, x):
            return x * self.factor

    d = Tunable.options(num_replicas=1, user_config={"factor": 5})
    h = serve.run(d.bind(), name="tune")
    assert h.remote(3).result(timeout=30) == 15
    # redeploy with new user_config reconfigures live replicas
    d2 = Tunable.options(num_replicas=1, user_config={"factor": 7})
    h = serve.run(d2.bind(), name="tune")
    deadline = time.time() + 10
    while time.time() < deadline:
        if h.remote(3).result(timeout=30) == 21:
            break
        time.sleep(0.2)
    assert h.remote(3).result(timeout=30) == 21
