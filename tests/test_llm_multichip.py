"""Token-identity matrix for the tensor-parallel engine (llm.multichip).

The multi-chip contract is behavioral, not numerical: an
``EngineConfig(tp=N)`` engine must emit EXACTLY the token stream the
single-chip engine emits — greedy and seeded sampling, speculative
decode on and off, through recompute preemption, failover
``resume_tokens`` and the prefix cache — while the host-side machinery
(ledger audit, watchdog, HBM gauges) keeps its invariants over the
sharded pool.  Per-head attention is bitwise identical under the head
split; only the two row-parallel psums reorder floating-point
reductions (~1 ulp/layer), which greedy argmax and fixed-seed sampling
absorb — these tests pin that.

Runs on jax host-platform CPU devices (conftest forces 8 via
``XLA_FLAGS=--xla_force_host_platform_device_count``), tp in {2, 4}
against the tp=1 reference.  Engines are lru_cached module-wide: each
(tp, spec, prefix) point jits once and every test reads it.
"""

import functools

import numpy as np
import pytest

from ray_tpu.llm.engine import EngineConfig, LLMEngine
from ray_tpu.llm.scheduler import SamplingParams
from ray_tpu.models.gptj import GPTJConfig, gptj_init


def _multi_device_cpu() -> bool:
    """Same capability probe as test_spmd_contracts: this jax build lacks
    the ``jax_num_cpu_devices`` config, so devices exist only if the
    conftest's XLA_FLAGS landed before jax initialized."""
    import jax

    return len(jax.devices("cpu")) >= 4


pytestmark = pytest.mark.skipif(
    not _multi_device_cpu(),
    reason="needs a >=4-device CPU mesh "
    "(XLA_FLAGS=--xla_force_host_platform_device_count, set by conftest)",
)

# tp=4-divisible geometry: 4 heads x head_dim 16, d_ff 256
TINY = GPTJConfig(
    vocab_size=128, seq_len=64, d_model=64, n_layers=2, n_heads=4,
    rotary_dim=8, dtype="float32", remat=False, attn_impl="xla",
    fused_loss=False,
)

GREEDY = SamplingParams(max_tokens=8, temperature=0.0)
SEEDED = SamplingParams(max_tokens=8, temperature=0.8, seed=42)
PROMPT = [1, 2, 3, 4, 5]


@functools.lru_cache(maxsize=1)
def _params():
    import jax

    return gptj_init(jax.random.PRNGKey(0), TINY)


def _engine(tp=1, **kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("num_blocks", 32)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_blocks_per_seq", 12)
    kw.setdefault("prefill_chunk", 8)
    return LLMEngine(TINY, _params(), EngineConfig(tp=tp, **kw))


def _drive(eng, reqs, max_steps=500):
    for _ in range(max_steps):
        if all(r.finished for r in reqs):
            return [list(r.out) for r in reqs]
        eng.step()
    raise AssertionError("engine did not finish")


@functools.lru_cache(maxsize=None)
def _matrix(tp: int, spec_k: int, prefix_cache: bool):
    """The standard request set (greedy + two seeded temperatures) on a
    fresh engine; returns (outputs, engine) — the engine stays alive for
    audit/ledger tests, the outputs are the identity fixture."""
    eng = _engine(tp=tp, spec_k=spec_k, prefix_cache=prefix_cache)
    reqs = [
        eng.submit(list(PROMPT), GREEDY),
        eng.submit([7, 8, 9], SEEDED),
        eng.submit(
            list(range(1, 13)),
            SamplingParams(max_tokens=6, temperature=0.6, seed=7, top_k=20),
        ),
    ]
    out = _drive(eng, reqs)
    return tuple(map(tuple, out)), eng


# --------------------------------------------------------------- identity


@pytest.mark.parametrize("tp", [2, 4])
def test_greedy_and_seeded_token_identity(tp):
    ref, _ = _matrix(1, 0, True)
    got, _ = _matrix(tp, 0, True)
    assert got == ref


# tier-1 budget (ISSUE 20): 11.0s measured at tp=2 — rides slow; the
# multichip-engine-smoke CI job runs this file in full on every push and
# single-chip spec identity stays gated by tests/test_llm_spec.py
@pytest.mark.slow
@pytest.mark.parametrize("tp", [2, 4])
def test_spec_decode_token_identity(tp):
    """Speculative decoding under tp: drafting is host-side, the sharded
    verify step must accept/correct exactly like single-chip."""
    ref, ref_eng = _matrix(1, 2, True)
    got, eng = _matrix(tp, 2, True)
    assert got == ref
    assert eng.stats()["spec_proposed"] > 0


# tier-1 budget (ISSUE 20): 6.7s measured across params — rides slow; the
# multichip-engine-smoke CI job runs this file in full, single-chip prefix
# identity stays gated by tests/test_llm_prefix.py, and the warm-path
# identity below stays in tier-1
@pytest.mark.slow
@pytest.mark.parametrize("tp", [2, 4])
def test_prefix_cache_off_token_identity(tp):
    ref, _ = _matrix(1, 0, False)
    got, _ = _matrix(tp, 0, False)
    assert got == ref


@pytest.mark.parametrize("tp", [2, 4])
def test_prefix_cache_warm_path_identity(tp):
    """A warm request sharing PROMPT as its prefix seeds from cached
    blocks (sharded CoW fork underneath) — still token-identical."""
    warm = SamplingParams(max_tokens=6, temperature=0.0)
    _, ref_eng = _matrix(1, 0, True)
    _, eng = _matrix(tp, 0, True)  # same traffic -> same cache state
    prompt = list(PROMPT) + [21, 22]
    ref = ref_eng.generate(prompt, warm)
    hits_before = eng.prefix_cache.stats()["hit_tokens"]
    got = eng.generate(prompt, warm)
    assert got == ref
    assert eng.prefix_cache.stats()["hit_tokens"] > hits_before


# tier-1 budget (ISSUE 20): 8.8s measured across params — rides slow; the
# multichip-engine-smoke CI job runs this file in full and single-chip
# preemption identity stays gated by tests/test_llm_spec.py
@pytest.mark.slow
@pytest.mark.parametrize("tp", [2, 4])
def test_preemption_recompute_identity(tp):
    """A pool too small for all completions forces recompute preemption;
    the sharded engine preempts and recovers to the same tokens."""

    def run(tp_):
        eng = _engine(
            tp=tp_, max_slots=3, num_blocks=13, block_size=4,
            max_blocks_per_seq=10,
        )
        prompts = [
            list(np.random.RandomState(s).randint(0, TINY.vocab_size, 8))
            for s in (5, 6, 7)
        ]
        reqs = [eng.submit(p, SamplingParams(max_tokens=16)) for p in prompts]
        out = _drive(eng, reqs)
        assert eng.stats()["preemptions"] > 0, "pool sized to force preemption"
        assert eng.pool.audit()["ok"]
        return out

    assert run(tp) == run(1)


@pytest.mark.parametrize("tp", [2, 4])
def test_failover_resume_tokens_identity(tp):
    """Mid-stream failover onto a tp replica: resuming from a tp=1
    replica's delivered prefix reproduces the unkilled run exactly."""
    (full, _seeded, _), _ = _matrix(1, 0, True)
    _, eng = _matrix(tp, 0, True)
    full = list(full)
    req = eng.submit(list(PROMPT), GREEDY, resume_tokens=full[:3])
    out = _drive(eng, [req])[0]
    assert out == full


# ----------------------------------------------------- sharded invariants


@pytest.mark.parametrize("tp", [2, 4])
def test_audit_and_watchdog_pass_sharded(tp):
    from ray_tpu.llm.watchdog import EngineWatchdog

    _, eng = _matrix(tp, 0, True)
    assert eng.pool.audit()["ok"]
    info = EngineWatchdog(eng, stall_deadline_s=30.0).check_once()
    assert info["audit"]["ok"]
    assert not info["stalled"]


@pytest.mark.parametrize("tp", [2, 4])
def test_per_device_hbm_ledger(tp):
    """Per-device attribution: the pool splits exactly 1/tp per device,
    the kv partition scales with local block bytes, params per device
    exceed the even split (replicated leaves are a full copy each), and
    the top-level (pool-wide) numbers match the tp=1 engine's."""
    _, ref_eng = _matrix(1, 0, True)
    _, eng = _matrix(tp, 0, True)
    led = eng.hbm_ledger()
    ref = ref_eng.hbm_ledger()
    assert led["pool_bytes"] == ref["pool_bytes"]
    assert led["params_bytes"] == ref["params_bytes"]
    per = led["per_device"]
    assert len(per) == tp
    assert sum(row["pool_bytes"] for row in per.values()) == led["pool_bytes"]
    for row in per.values():
        assert row["pool_bytes"] == led["pool_bytes"] // tp
        assert row["params_bytes"] > led["params_bytes"] // tp
        # the local kv partition covers the usable local blocks
        bb_local = row["pool_bytes"] // eng.pool.cfg.num_blocks
        usable = (eng.pool.cfg.num_blocks - 1) * bb_local
        assert row["seq_bytes"] + row["cache_bytes"] + row["free_bytes"] == usable
    assert "per_device" not in ref


def test_hbm_gauges_carry_device_tag():
    """tp>1 publishes the same gauge NAMES split by a device tag (RL012:
    no new names); the untagged series stays pool-wide."""
    from ray_tpu.util import metrics as um

    _, eng = _matrix(2, 0, True)
    eng._publish_gauges()
    led = eng.hbm_ledger()
    data = {
        m.name: m._snapshot()["data"]
        for m in um._registry
        if m.name == "llm_hbm_kv_pool_bytes"
    }["llm_hbm_kv_pool_bytes"]
    assert data.get("") == led["pool_bytes"]  # untagged = pool-wide
    tagged = {k: v for k, v in data.items() if "device" in k}
    assert len(tagged) >= 2
    assert sum(v for v in tagged.values() if v == led["pool_bytes"] // 2) \
        == led["pool_bytes"]


# tier-1 budget (ISSUE 20): 9.6s measured across params — rides slow; the
# multichip-engine-smoke CI job runs this file in full and the swap contract
# stays gated by tests/test_llm_weight_swap.py + the rlhf hot-swap tests
@pytest.mark.slow
@pytest.mark.parametrize("tp", [2, 4])
def test_update_weights_sharded_hot_swap(tp):
    """update_weights routes through the tp runner's prepare_params:
    the swap lands sharded and the engine continues token-identical to
    a single-chip engine born with the new weights."""
    import jax

    eng = _engine(tp=tp)
    eng.warmup()
    new = gptj_init(jax.random.PRNGKey(1), TINY)
    assert eng.update_weights(new) == 1
    ref_eng = LLMEngine(
        TINY, new,
        EngineConfig(max_slots=3, num_blocks=32, block_size=4,
                     max_blocks_per_seq=12, prefill_chunk=8),
    )
    want = ref_eng.generate(list(PROMPT), GREEDY)
    assert eng.generate(list(PROMPT), GREEDY) == want


def test_divisibility_validation():
    from ray_tpu.llm.cache import CacheConfig
    from ray_tpu.llm.multichip import (
        ShardedKVBlockPool,
        TensorParallelPagedModelRunner,
    )

    with pytest.raises(ValueError, match="not divisible"):
        ShardedKVBlockPool(
            CacheConfig(num_blocks=8, block_size=4, max_blocks_per_seq=4),
            n_layers=2, n_heads=4, head_dim=16, tp=3,
        )
    with pytest.raises(ValueError, match="not divisible"):
        TensorParallelPagedModelRunner(TINY, _params(), 4, tp=3)
