"""Workflow durability tests (reference: ``python/ray/workflow/tests``
themes: run, checkpoint-per-step, resume-skips-done-steps, status)."""

import os

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode, MultiOutputNode


@pytest.fixture
def wf_storage(tmp_path, ray_start_regular):
    return str(tmp_path / "wf")


def test_run_and_output(wf_storage):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    @ray_tpu.remote
    def inc(x):
        return x + 1

    dag = inc.bind(double.bind(5))
    out = workflow.run(dag, workflow_id="w1", storage=wf_storage)
    assert out == 11
    assert workflow.get_status("w1", wf_storage) == workflow.STATUS_SUCCESSFUL
    assert workflow.get_output("w1", wf_storage) == 11
    assert ("w1", workflow.STATUS_SUCCESSFUL) in workflow.list_all(wf_storage)


def test_input_args_flow(wf_storage):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    with InputNode() as inp:
        dag = add.bind(inp, 10)
    assert workflow.run(dag, 7, workflow_id="w2", storage=wf_storage) == 17


def test_resume_skips_completed_steps(wf_storage):
    """A step that fails once leaves earlier checkpoints; resume reruns only
    the unfinished tail."""
    marker = os.path.join(wf_storage, "fail_once")

    @ray_tpu.remote
    def expensive(x):
        # count executions via a side file
        path = os.environ["WF_COUNT_FILE"]
        n = int(open(path).read()) if os.path.exists(path) else 0
        with open(path, "w") as f:
            f.write(str(n + 1))
        return x * 10

    @ray_tpu.remote
    def flaky(x, marker):
        if not os.path.exists(marker):
            with open(marker, "w") as f:
                f.write("x")
            raise RuntimeError("transient failure")
        return x + 1

    os.makedirs(wf_storage, exist_ok=True)
    count_file = os.path.join(wf_storage, "count")
    os.environ["WF_COUNT_FILE"] = count_file

    dag = flaky.bind(expensive.bind(4), marker)
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="w3", storage=wf_storage)
    assert workflow.get_status("w3", wf_storage) == workflow.STATUS_FAILED
    assert int(open(count_file).read()) == 1  # expensive ran once

    out = workflow.resume("w3", wf_storage)
    assert out == 41
    assert int(open(count_file).read()) == 1  # NOT re-executed on resume
    assert workflow.get_status("w3", wf_storage) == workflow.STATUS_SUCCESSFUL


def test_resume_of_finished_workflow_returns_output(wf_storage):
    @ray_tpu.remote
    def one():
        return 1

    workflow.run(one.bind(), workflow_id="w4", storage=wf_storage)
    assert workflow.resume("w4", wf_storage) == 1


def test_multi_output_workflow(wf_storage):
    @ray_tpu.remote
    def sq(x):
        return x * x

    dag = MultiOutputNode([sq.bind(2), sq.bind(3)])
    assert workflow.run(dag, workflow_id="w5", storage=wf_storage) == [4, 9]


def test_branches_run_concurrently(ray_start_regular, tmp_path):
    """Independent branches must overlap (reference: the workflow
    executor's in-flight task set, not a sequential topological walk)."""
    import time

    @ray_tpu.remote
    def slow(tag):
        time.sleep(1.2)
        return tag

    @ray_tpu.remote
    def join(a, b, c):
        return [a, b, c]

    dag = join.bind(slow.bind("a"), slow.bind("b"), slow.bind("c"))
    t0 = time.monotonic()
    out = workflow.run(dag, workflow_id="wf_conc", storage=str(tmp_path))
    dt = time.monotonic() - t0
    assert out == ["a", "b", "c"]
    assert dt < 3.0, f"branches ran sequentially ({dt:.1f}s for 3x1.2s steps)"


def test_step_retries_via_task_options(ray_start_regular, tmp_path):
    """A step's retry budget is its task max_retries: a step that fails
    twice then succeeds completes the workflow without a resume."""
    marker = tmp_path / "attempts"

    @ray_tpu.remote(max_retries=3)
    def flaky():
        n = int(marker.read_text()) if marker.exists() else 0
        marker.write_text(str(n + 1))
        if n < 2:
            raise RuntimeError(f"boom {n}")
        return "recovered"

    out = workflow.run(flaky.bind(), workflow_id="wf_retry", storage=str(tmp_path), max_step_retries=3)
    assert out == "recovered"
    assert int(marker.read_text()) == 3


def test_events_logged_and_pushed(ray_start_regular, tmp_path):
    @ray_tpu.remote
    def stepa():
        return 1

    @ray_tpu.remote
    def stepb(x):
        return x + 1

    live = []
    out = workflow.run(
        stepb.bind(stepa.bind()),
        workflow_id="wf_events",
        storage=str(tmp_path),
        on_event=live.append,
    )
    assert out == 2
    events = workflow.get_events("wf_events", storage=str(tmp_path))
    types = [(e["type"], e["step_id"].split("_")[1]) for e in events]
    assert ("step_started", "stepa") in types
    assert ("step_completed", "stepa") in types
    assert ("step_completed", "stepb") in types
    assert [e["type"] for e in live] == [e["type"] for e in events]
    assert all("time" in e for e in events)
