"""Core task/object API tests (modeled on the reference's
``python/ray/tests/test_basic.py`` family)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError, RayTaskError


def test_put_get(ray_start_regular):
    ref = ray_tpu.put(42)
    assert ray_tpu.get(ref) == 42
    ref2 = ray_tpu.put({"a": [1, 2, 3], "b": "x"})
    assert ray_tpu.get(ref2) == {"a": [1, 2, 3], "b": "x"}


def test_put_get_large_numpy(ray_start_regular):
    arr = np.arange(1_000_000, dtype=np.float32)  # 4MB -> shm path
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(arr, out)
    # zero-copy: the result should be backed by shared memory (not writeable)
    assert out.flags["WRITEABLE"] is False or out.base is not None


def test_simple_task(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1)) == 2
    refs = [f.remote(i) for i in range(20)]
    assert ray_tpu.get(refs) == list(range(1, 21))


def test_task_with_ref_args(ray_start_regular):
    @ray_tpu.remote
    def f(x, y):
        return x + y

    a = ray_tpu.put(10)
    b = f.remote(a, 5)
    c = f.remote(b, a)
    assert ray_tpu.get(c) == 25


def test_task_chain_dependencies(ray_start_regular):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    ref = ray_tpu.put(0)
    for _ in range(10):
        ref = inc.remote(ref)
    assert ray_tpu.get(ref) == 10


def test_task_error_propagation(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    @ray_tpu.remote
    def dependent(x):
        return x

    with pytest.raises(ValueError, match="kaboom"):
        ray_tpu.get(boom.remote())
    # error poisons dependents
    with pytest.raises(ValueError, match="kaboom"):
        ray_tpu.get(dependent.remote(boom.remote()))


def test_task_error_is_raytaskerror(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise KeyError("k")

    with pytest.raises(RayTaskError):
        ray_tpu.get(boom.remote())


def test_multiple_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_num_cpus_options(ray_start_regular):
    @ray_tpu.remote(num_cpus=2)
    def f():
        return 1

    assert ray_tpu.get(f.remote()) == 1
    assert ray_tpu.get(f.options(num_cpus=1).remote()) == 1


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(30)

    ref = slow.remote()
    with pytest.raises(GetTimeoutError):
        ray_tpu.get(ref, timeout=0.2)
    ray_tpu.cancel(ref, force=True)


def test_wait(ray_start_regular):
    @ray_tpu.remote
    def fast():
        return 1

    @ray_tpu.remote
    def slow():
        time.sleep(60)
        return 2

    f, s = fast.remote(), slow.remote()
    ready, not_ready = ray_tpu.wait([f, s], num_returns=1, timeout=10)
    assert ready == [f]
    assert not_ready == [s]
    ready, not_ready = ray_tpu.wait([f, s], num_returns=2, timeout=0.2)
    assert ready == [f] and not_ready == [s]
    ray_tpu.cancel(s, force=True)


def test_nested_tasks(ray_start_regular):
    @ray_tpu.remote
    def inner(x):
        return x * 2

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 1

    assert ray_tpu.get(outer.remote(10)) == 21


def test_task_returns_ref(ray_start_regular):
    @ray_tpu.remote
    def make():
        return ray_tpu.put(123)

    ref_of_ref = make.remote()
    inner_ref = ray_tpu.get(ref_of_ref)
    assert ray_tpu.get(inner_ref) == 123


def test_large_arg_promoted(ray_start_regular):
    big = np.ones(500_000, dtype=np.float64)  # 4MB by-value arg

    @ray_tpu.remote
    def s(x):
        return float(x.sum())

    assert ray_tpu.get(s.remote(big)) == 500_000.0


def test_cluster_resources(ray_start_regular):
    res = ray_tpu.cluster_resources()
    assert res["CPU"] == 4.0


def test_cannot_call_remote_directly(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(TypeError):
        f()


def test_put_objectref_rejected(ray_start_regular):
    ref = ray_tpu.put(1)
    with pytest.raises(TypeError):
        ray_tpu.put(ref)


def test_cancel_pending(ray_start_regular):
    @ray_tpu.remote
    def blocker():
        time.sleep(60)

    @ray_tpu.remote
    def victim():
        return 1

    # fill all 4 cpus
    blockers = [blocker.remote() for _ in range(4)]
    v = victim.remote()
    ray_tpu.cancel(v)
    from ray_tpu.exceptions import TaskCancelledError

    with pytest.raises(TaskCancelledError):
        ray_tpu.get(v, timeout=10)
    for b in blockers:
        ray_tpu.cancel(b, force=True)


def test_dag_bind_execute(ray_start_regular):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    node = add.bind(add.bind(1, 2), 4)
    assert ray_tpu.get(node.execute()) == 7


def test_config_flag_tiers(monkeypatch):
    """The three override tiers (default < RAY_TPU_ env < _system_config)
    apply to every dataclass field, including the round-3 knobs that were
    previously hardcoded (reference: RAY_CONFIG flag system)."""
    from ray_tpu._private.config import Config

    cfg = Config()
    assert cfg.object_transfer_chunk_bytes == 8 * 1024 * 1024
    assert cfg.collective_ring_threshold_bytes == 1 << 22
    assert cfg.serve_handle_max_retries == 4
    monkeypatch.setenv("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", "1048576")
    monkeypatch.setenv("RAY_TPU_DASHBOARD_PORT", "9999")
    cfg.apply_overrides({"serve_handle_max_retries": 7})
    assert cfg.object_transfer_chunk_bytes == 1048576  # env tier
    assert cfg.dashboard_port == 9999
    assert cfg.serve_handle_max_retries == 7  # _system_config tier wins
    with pytest.raises(ValueError, match="Unknown _system_config"):
        cfg.apply_overrides({"not_a_flag": 1})
