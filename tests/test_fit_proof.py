"""AOT fit-proof machinery (BASELINE.md north star: GPT-J-6B on v5e-8).
The 6B compile itself runs in bench.py's subprocess; here the same code
path is proven on a tiny config against the virtual 8-device CPU mesh."""

from ray_tpu.models.gpt import GPTConfig
from ray_tpu.parallel.fit_proof import fit_report


def test_fit_report_tiny_config_compiles_with_memory_analysis():
    cfg = GPTConfig(vocab_size=2048, seq_len=128, d_model=128, n_layers=2, n_heads=4)
    rep = fit_report(cfg, n_devices=8, batch=8)
    assert rep["compiles"] is True
    assert rep["n_devices"] == 8
    assert rep["model_params"] > 500_000
    # memory analysis may be unavailable on some backends; when present the
    # numbers must be sane (>0, args dominated by fp32 params + adam moments)
    if "per_chip_bytes" in rep:
        assert rep["per_chip_bytes"] > 0
        assert rep["argument_bytes"] > rep["model_params"] * 12 / 8 * 0.5
