"""Telemetry hot-path contracts (PR 11 rebuild).

Three layers of proof that observability-ON costs the task plane nothing:

* **Static (lint fixture):** the emit paths — ``events.record``,
  ``Counter.inc`` / ``Gauge.set`` / ``Histogram.observe``, ``tracing.span``
  dispatch — acquire NO shared lock, verified against the real sources
  through the raylint phase-1 index (``trans_lock_acqs``), and the new
  events-collector drainer thread is visible to RL011's daemon-path
  analysis.
* **Concurrency stress:** N threads emitting events and bumping counters
  while the collector folds rings — no lost, duplicated, or
  reordered-within-thread events; the per-ring drop counter is EXACT
  under overflow (single-writer accounting, not the old advisory RMW).
* **Crash integrity:** a SIGTERM crash-flush fired mid-stream (emitters
  still running) writes a readable JSONL whose events are unique and
  in-order per thread.
"""

import ast
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from ray_tpu._private import events
from ray_tpu.util import metrics as um
from ray_tpu.util import tracing


@pytest.fixture
def fresh_ring():
    st = events.stats()
    events.clear()
    events.set_enabled(True)
    yield
    events.configure(capacity=st["capacity"])
    events.set_enabled(st["enabled"])
    events.clear()


# ---------------------------------------------------------------------------
# static: the emit paths acquire no shared lock (raylint index fixture)
# ---------------------------------------------------------------------------


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HOT_PATHS = (
    ("ray_tpu/_private/events.py", "ray_tpu._private.events", "record"),
    ("ray_tpu/util/metrics.py", "ray_tpu.util.metrics", "Counter.inc"),
    ("ray_tpu/util/metrics.py", "ray_tpu.util.metrics", "Gauge.set"),
    ("ray_tpu/util/metrics.py", "ray_tpu.util.metrics", "Histogram.observe"),
    ("ray_tpu/util/tracing.py", "ray_tpu.util.tracing", "span"),
    # profiling plane (ISSUE 13): waterfall stamps ride every sampled
    # submit/dispatch/exec hop, and the step-profiler note() runs per
    # jitted call — all must stay lock-free like the paths above
    ("ray_tpu/util/waterfall.py", "ray_tpu.util.waterfall", "maybe_start"),
    ("ray_tpu/util/waterfall.py", "ray_tpu.util.waterfall", "stamp"),
    ("ray_tpu/util/device_prof.py", "ray_tpu.util.device_prof",
     "JitProfiler.note"),
    # object-plane flight deck (ISSUE 19): the core.object.* emit helper
    # rides every put/map/unmap/pull on the data plane, and the reader
    # pin ledger notes/drops a pin per zero-copy read — all lock-free
    ("ray_tpu/_private/events.py", "ray_tpu._private.events", "emit"),
    ("ray_tpu/_private/shm_store.py", "ray_tpu._private.shm_store",
     "note_pin"),
    ("ray_tpu/_private/shm_store.py", "ray_tpu._private.shm_store",
     "drop_pin"),
    # request latency attribution plane (ISSUE 20): the phase ledger is
    # charged on every admission / prefill chunk / decode step / preempt
    # under the engine lock's critical sections — the stamp itself must
    # acquire nothing (a list add + two float ops; the fold at finish
    # pays for assembly, never the per-step charge)
    ("ray_tpu/util/phases.py", "ray_tpu.util.phases", "new_ledger"),
    ("ray_tpu/util/phases.py", "ray_tpu.util.phases", "charge"),
)


def _real_index():
    from ray_tpu._lint.core import FileContext
    from ray_tpu._lint.index import build_index

    ctxs = []
    for rel in sorted({p for p, _m, _q in HOT_PATHS}):
        path = os.path.join(REPO, rel)
        text = open(path).read()
        ctxs.append(FileContext(path, rel, text, ast.parse(text)))
    return build_index(ctxs, display_root=REPO)


def test_emit_paths_acquire_no_shared_lock():
    """The zero-cost contract, mechanized: every hot-path function must
    reach ZERO lock acquisitions through the whole-program call graph.
    A lock creeping back into record()/inc()/set()/observe()/span() —
    directly or via a helper — fails here, naming the acquisition."""
    idx = _real_index()
    for _rel, module, qualname in HOT_PATHS:
        info = idx.functions.get(f"{module}:{qualname}")
        assert info is not None, f"index lost {module}:{qualname}"
        acqs = idx.trans_lock_acqs(info)
        assert not acqs, (
            f"telemetry hot path {module}:{qualname} acquires lock(s): "
            f"{sorted(a[0] for a in acqs)} — the emit path must stay "
            "lock-free (OBSERVABILITY.md hot-path architecture)"
        )


def test_collector_thread_visible_to_daemon_analysis():
    """RL011 coverage of the new drainer: the events-collector thread
    target must be in the index's daemon-reachable set so
    blocking-under-lock analysis applies to everything it calls."""
    idx = _real_index()
    daemon = idx.daemon_reachable()
    keys = {getattr(k, "key", k) for k in daemon}
    assert any("_collector_loop" in str(k) for k in keys), (
        "events._collector_loop is not daemon-reachable in the index — "
        "RL011 cannot see the drainer thread"
    )


def _full_tree_index():
    """Whole-package index (cached): the LOCKFREE verification needs the
    real thread roots, which span head/worker/serve modules."""
    global _FULL_IDX
    try:
        return _FULL_IDX
    except NameError:
        pass
    import pathlib

    from ray_tpu._lint.core import FileContext, iter_python_files
    from ray_tpu._lint.index import build_index

    # iter_python_files is the SAME collector the lint gate uses (skip
    # dirs, display paths) — this test must analyze exactly what the
    # self-lint run analyzes
    root = pathlib.Path(REPO)
    ctxs = []
    for abs_path, display in iter_python_files(
        [root / "ray_tpu"], display_root=root
    ):
        text = abs_path.read_text()
        ctxs.append(FileContext(abs_path, display, text, ast.parse(text)))
    _FULL_IDX = build_index(ctxs, display_root=root)
    return _FULL_IDX


def test_lockfree_declarations_verified_against_real_sources():
    """The RL017 contract, index-backed like the zero-lock test above:
    every LOCKFREE entry in the tree matches accessed state, every BARE
    entry really is single-writer (≤1 writing thread root in the whole-
    program thread model), and every ':atomic' entry has no
    read-modify-write site. A declaration drifting from the code fails
    tier-1 here AND in the self-lint gate — by construction, since this
    re-runs the verifier the lint gate uses."""
    from ray_tpu._lint import concurrency

    idx = _full_tree_index()
    model = concurrency.get_model(idx)
    decls = idx.lockfree_decls()
    assert decls, "the tree lost its LOCKFREE declarations"
    entries = [
        (module, e) for module, es, _n, _c in decls for e in es
    ]
    # the PR 11 hot-path declarations specifically must exist
    assert any(e.startswith("_rings") for _m, e in entries)
    checked = 0
    for module, entry in entries:
        key, qual = concurrency.parse_lockfree(entry)
        if "." not in key:
            key = f"{module}.{key}"
        states = model.by_display.get(key)
        assert states, f"LOCKFREE entry {entry!r} matches no accessed state"
        accs = [a for st in states for a in model.accesses[st]]
        writes = [a for a in accs if a.kind in ("store", "aug", "mutate")]
        if qual is None:
            wroots = {a.root for a in writes}
            assert len(wroots) <= 1, (
                f"bare LOCKFREE entry {entry!r} is written from "
                f"{sorted(wroots)} — no longer single-writer"
            )
        else:
            assert qual == "atomic", entry
            bad = [a for a in writes if a.kind == "aug"]
            assert not bad, (
                f"':atomic' LOCKFREE entry {entry!r} has a "
                "read-modify-write site"
            )
        checked += 1
    assert checked >= 8  # head, events, worker_main, waterfall, ... all in


# ---------------------------------------------------------------------------
# concurrency stress: no lost / duplicated / reordered-within-thread events
# ---------------------------------------------------------------------------


def _emit(etype, thread_idx, n):
    for i in range(n):
        events.record(etype, t=thread_idx, i=i)


def test_threads_no_lost_dup_reorder(fresh_ring):
    events.configure(capacity=8192)
    n_threads, per = 8, 1500
    threads = [
        threading.Thread(
            target=_emit, args=("stress.a", k, per), name=f"obs-stress-{k}"
        )
        for k in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive()
    evs = [e for e in events.snapshot() if e["type"] == "stress.a"]
    assert len(evs) == n_threads * per  # nothing lost
    assert len({e["seq"] for e in evs}) == len(evs)  # nothing duplicated
    # snapshot is globally seq-ordered, and within each emitting thread
    # the payload order must match emission order exactly
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs)
    per_thread = {k: [] for k in range(n_threads)}
    for e in evs:
        per_thread[e["t"]].append(e["i"])
    for k, idxs in per_thread.items():
        assert idxs == list(range(per)), f"thread {k} reordered/lost events"


def test_drop_counter_exact_on_overflow(fresh_ring):
    events.configure(capacity=64)
    n_threads, per = 4, 500
    threads = [
        threading.Thread(
            target=_emit, args=("stress.b", k, per), name=f"obs-drop-{k}"
        )
        for k in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    rows = {
        r["thread"]: r for r in events.ring_stats() if r["thread"].startswith("obs-drop-")
    }
    assert len(rows) == n_threads
    for name, r in rows.items():
        # single-writer accounting: EXACTLY emitted - capacity dropped,
        # and the ring holds exactly the newest `capacity`
        assert r["dropped"] == per - 64, (name, r)
        assert r["size"] == 64, (name, r)
    # each surviving window is the newest 64 of its thread, in order
    evs = [e for e in events.snapshot() if e["type"] == "stress.b"]
    per_thread = {}
    for e in evs:
        per_thread.setdefault(e["t"], []).append(e["i"])
    for k, idxs in per_thread.items():
        assert idxs == list(range(per - 64, per)), f"thread {k} kept wrong window"


def test_collector_folds_dead_thread_rings(fresh_ring):
    events.configure(capacity=256)
    stats0 = events.stats()
    threads = [
        threading.Thread(
            target=_emit, args=("stress.c", k, 50), name=f"obs-fold-{k}"
        )
        for k in range(5)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    rings_before = events.stats()["rings"]
    events.collector_pass_for_tests()
    st = events.stats()
    # the dead threads' rings are gone, their events are not
    assert st["rings"] <= rings_before - 5
    evs = [e for e in events.snapshot() if e["type"] == "stress.c"]
    assert len(evs) == 5 * 50
    assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)
    assert st["dropped"] == stats0["dropped"]  # folding drops nothing here


def test_events_dropped_metric_published(fresh_ring):
    events.configure(capacity=16)

    t = threading.Thread(
        target=_emit, args=("stress.d", 0, 116), name="obs-metric-drop"
    )
    t.start()
    t.join(timeout=60)
    events.collector_pass_for_tests()
    # the lazy counter exists and carries (at least) this test's 100 drops
    drop_counters = [
        m for m in um._registry if m.name == "events_dropped"
    ]
    assert drop_counters, "events_dropped counter was never created"
    total = sum(
        v for m in drop_counters for v in m._snapshot()["data"].values()
    )
    assert total >= 100


def test_counter_concurrent_exact():
    c = um.Counter("obs_hotpath_exact_total", "stress", tag_keys=("lane",))
    n_threads, per = 8, 5000

    def bump(k):
        for _ in range(per):
            c.inc(1.0, tags={"lane": str(k % 2)})

    threads = [threading.Thread(target=bump, args=(k,)) for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    data = c._snapshot()["data"]
    total = sum(data.values())
    # thread-local cells are single-writer: the merge is EXACT, no lost
    # increments despite zero locks on the inc path
    assert total == n_threads * per
    assert data['{"lane":"0"}'] == data['{"lane":"1"}']


def test_dead_thread_cells_compact_without_losing_counts():
    """Thread churn (serve's per-stream proxy threads) must not leak
    metric cells: dead threads' cells fold into the base data at
    snapshot time — totals exactly preserved, cell list shrunk."""
    c = um.Counter("obs_hotpath_churn_total", "stress")
    for wave in range(3):
        threads = [
            threading.Thread(target=lambda: [c.inc() for _ in range(100)])
            for _ in range(10)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert sum(c._snapshot()["data"].values()) == (wave + 1) * 1000
    # after the folds, the dead threads' cells are gone (only cells of
    # still-alive threads — e.g. this one's, if it ever emitted — remain)
    assert len(c._cells) <= 1
    assert sum(c._snapshot()["data"].values()) == 3000


def test_histogram_concurrent_exact():
    h = um.Histogram(
        "obs_hotpath_exact_hist_s", "stress", boundaries=(0.1, 1.0)
    )
    n_threads, per = 6, 3000

    def observe():
        for i in range(per):
            h.observe(0.05 if i % 2 else 5.0)

    threads = [threading.Thread(target=observe) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    p = h.percentiles()
    assert p["count"] == n_threads * per


def test_unsampled_context_records_nothing(monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "0")
    tracing.clear()
    with tracing.trace_context() as rid:
        assert tracing.current_request_id() == rid
        with tracing.span("invisible", x=1):
            pass
    assert not any(s["name"] == "invisible" for s in tracing.get_spans())
    # the context ships AS THE TOKEN (by reference): forensics keep the
    # request id downstream, the sampling decision is pinned (no
    # half-sampled traces), and spans stay free everywhere
    ctx = tracing.mint_context()
    assert type(ctx) is tracing.UnsampledContext
    assert tracing.context_for_spec(ctx) is ctx
    import pickle

    clone = pickle.loads(pickle.dumps(ctx))  # rides task specs
    assert clone.request_id == ctx.request_id and not clone.sampled
    # a lazy root whose id lands unsampled also ships a token
    lazy = tracing.task_context(None, b"\x00" * 16)
    assert type(tracing.context_for_spec(lazy)) is tracing.UnsampledContext
    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "1")
    with tracing.trace_context():
        with tracing.span("visible"):
            pass
    assert any(s["name"] == "visible" for s in tracing.get_spans())


def test_waterfall_unsampled_path_costs_like_disabled_record():
    """The satellite pin: an UNSAMPLED task's waterfall cost (one type
    check in maybe_start) must stay in the same class as a disabled
    record() — the cheapest thing the telemetry plane knows how to do.
    Generous multiplier: this box's timing noise is ±30%, the contract
    is about orders of magnitude (a lock or an allocation creeping into
    the unsampled path shows up as 10-100x, not 3x)."""
    from ray_tpu.obs import measure_overhead

    res = measure_overhead(n=30_000)
    budget = max(res["event_record_disabled_ns"] * 5, 1_000.0)
    assert res["waterfall_unsampled_ns"] <= budget, res
    # sampled stamps are clock+append — same class as a counter inc
    assert res["waterfall_stamp_ns"] <= max(
        res["counter_inc_ns"] * 10, 5_000.0
    ), res
    # the step profiler emits a tagged observe + a cache-size read per
    # jitted call (ms-scale steps): must stay well under 100us
    assert res["device_prof_note_ns"] <= 100_000.0, res


def test_waterfall_maybe_start_only_stamps_sampled_dicts():
    from ray_tpu.util import waterfall as wfl

    assert wfl.maybe_start(None) is None
    assert wfl.maybe_start(tracing.UnsampledContext("ab")) is None
    lazy = tracing.LazyTaskContext(b"\x01" * 16)
    assert wfl.maybe_start(lazy) is None  # rootless ships nothing
    wf = wfl.maybe_start({"request_id": "ab"})
    assert isinstance(wf, list) and len(wf) == 1
    wfl.stamp(wf)
    assert len(wf) == 2 and wf[1] >= wf[0]


def test_lazy_task_context_materializes_on_demand():
    task_id = bytes(range(16))
    ctx = tracing.task_context(None, task_id)
    assert type(ctx) is tracing.LazyTaskContext
    assert ctx._rid is None  # nothing paid yet
    rid = ctx.request_id
    assert rid == task_id.hex()[:16]
    assert ctx.get("request_id") == rid
    # a shipped context is returned as-is (by reference, no copy)
    shipped = {"request_id": "abc123"}
    assert tracing.task_context(shipped, task_id) is shipped
    assert tracing.context_for_spec(shipped) is shipped


# ---------------------------------------------------------------------------
# SIGTERM crash-flush fired mid-stream
# ---------------------------------------------------------------------------


def test_sigterm_crash_flush_mid_stream(tmp_path):
    """Emitters on several threads are mid-append when SIGTERM lands on
    the main thread: the flush must still write every thread's ring as
    one seq-ordered JSONL — unique seqs, per-thread order intact — with
    the drop accounting in the header."""
    code = (
        "import os, signal, threading, time\n"
        "from ray_tpu._private import events\n"
        "events.configure(capacity=512)\n"
        "events.install_crash_handlers()\n"
        "stop = False\n"
        "def emit(k):\n"
        "    i = 0\n"
        "    while not stop:\n"
        "        events.record('mid.stream', t=k, i=i)\n"
        "        i += 1\n"
        "for k in range(4):\n"
        "    threading.Thread(target=emit, args=(k,), daemon=True).start()\n"
        "time.sleep(0.5)\n"
        "events.record('mid.main')\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
    )
    env = dict(os.environ, RAY_TPU_EVENTS_DIR=str(tmp_path), JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, timeout=60,
        capture_output=True, cwd=REPO,
    )
    assert proc.returncode != 0  # died by the signal
    files = [f for f in os.listdir(tmp_path) if f.endswith(".jsonl")]
    assert len(files) == 1, (files, proc.stderr.decode()[-500:])
    lines = [json.loads(x) for x in open(tmp_path / files[0])]
    header, evs = lines[0], lines[1:]
    assert header["reason"] == "sigterm"
    assert header["rings"] >= 4
    types = {e["type"] for e in evs}
    assert "mid.stream" in types and "crash.sigterm" in types
    seqs = [e["seq"] for e in evs]
    assert len(set(seqs)) == len(seqs)  # no duplicates across rings
    assert seqs == sorted(seqs)  # global emission order
    per_thread: dict = {}
    for e in evs:
        if e["type"] == "mid.stream":
            per_thread.setdefault(e["t"], []).append(e["i"])
    assert len(per_thread) == 4
    for k, idxs in per_thread.items():
        # each thread's surviving window is contiguous and in order
        assert idxs == list(range(idxs[0], idxs[0] + len(idxs))), (
            f"thread {k} events reordered or lost inside the flush"
        )
