"""Multi-host control plane: TCP transport, node agents, remote drivers.

Reference: ``python/ray/_private/services.py:1421,1485`` (head + node
launchers), ``scripts/scripts.py:566`` (``ray start``), and the two-node
cluster fixtures of ``python/ray/tests/conftest.py``. Here "hosts" are
separate processes on loopback TCP — the same wire path a real second host
uses (workers/agents never touch the head's unix socket or shm).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.config import resolve_authkey
from ray_tpu._private.head import Head
from ray_tpu._private.node_agent import NodeAgent


@pytest.fixture
def tcp_cluster():
    """In-process head with a TCP listener + one agent 'host' (CPU:2);
    the head node itself has no CPU so all tasks land on the agent node."""
    authkey = resolve_authkey()
    session = tempfile.mkdtemp(prefix="ray_tpu_tcp_")
    head = Head(os.path.join(session, "head.sock"), authkey=authkey)
    head.start()
    host, port = head.listen_tcp("127.0.0.1", 0)
    head.add_node({"CPU": 0.0})
    agent = NodeAgent(f"{host}:{port}", authkey, resources={"CPU": 2.0}).start()
    yield {"head": head, "agent": agent, "address": f"{host}:{port}"}
    try:
        ray_tpu.shutdown()
    except Exception:
        pass
    agent.shutdown()
    head.shutdown()


def test_tasks_run_on_remote_node(tcp_cluster):
    ray_tpu.init(address=tcp_cluster["address"])

    @ray_tpu.remote
    def where():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    nodes = set(ray_tpu.get([where.remote() for _ in range(6)], timeout=60))
    assert nodes == {tcp_cluster["agent"].node_id_bin.hex()}


def test_large_objects_cross_the_wire(tcp_cluster):
    ray_tpu.init(address=tcp_cluster["address"])
    big = np.arange(400_000, dtype=np.float64)  # ~3.2MB >> inline threshold
    ref = ray_tpu.put(big)
    np.testing.assert_array_equal(ray_tpu.get(ref, timeout=60), big)

    @ray_tpu.remote
    def transform(x):
        return x * 2.0

    out = ray_tpu.get(transform.remote(ref), timeout=60)
    np.testing.assert_array_equal(out, big * 2.0)


def test_actor_on_remote_node_with_state(tcp_cluster):
    ray_tpu.init(address=tcp_cluster["address"])

    @ray_tpu.remote
    class Acc:
        def __init__(self):
            self.v = 0

        def add(self, k):
            self.v += k
            return self.v

    a = Acc.remote()
    assert ray_tpu.get(a.add.remote(3), timeout=60) == 3
    assert ray_tpu.get(a.add.remote(4), timeout=60) == 7


def test_agent_death_removes_node(tcp_cluster):
    ray_tpu.init(address=tcp_cluster["address"])

    @ray_tpu.remote
    def ping():
        return 1

    assert ray_tpu.get(ping.remote(), timeout=60) == 1
    assert len([n for n in ray_tpu.nodes() if n["Alive"]]) == 2
    tcp_cluster["agent"].shutdown()
    deadline = time.time() + 20
    while time.time() < deadline:
        if len([n for n in ray_tpu.nodes() if n["Alive"]]) == 1:
            break
        time.sleep(0.2)
    assert len([n for n in ray_tpu.nodes() if n["Alive"]]) == 1


def test_train_spreads_across_hosts(tcp_cluster):
    """JaxTrainer with num_workers=2 SPREAD: one train worker per 'host'."""
    # give the head node capacity so SPREAD has two viable nodes
    tcp_cluster["head"].add_node({"CPU": 2.0})
    ray_tpu.init(address=tcp_cluster["address"])

    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    marker_dir = tempfile.mkdtemp(prefix="mh_marks_")

    def loop():
        import os as _os

        import ray_tpu as rt
        from ray_tpu import train

        ctx = train.get_context()
        rank = ctx.get_world_rank()
        node = rt.get_runtime_context().get_node_id()
        with open(_os.path.join(loop.marker_dir, f"rank{rank}"), "w") as f:
            f.write(node)
        train.report({"rank": rank})

    loop.marker_dir = marker_dir

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(
            num_workers=2, placement_strategy="SPREAD", resources_per_worker={"CPU": 1}
        ),
        run_config=RunConfig(storage_path=tempfile.mkdtemp(prefix="mh_train_")),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    nodes = {open(os.path.join(marker_dir, f"rank{r}")).read() for r in range(2)}
    assert len(nodes) == 2, f"train workers were not spread across hosts: {nodes}"


CLI_ENV = dict(os.environ, PYTHONPATH="/root/repo" + os.pathsep + os.environ.get("PYTHONPATH", ""))


def test_cli_head_node_driver_roundtrip(tmp_path):
    """The real deployment shape: `ray_tpu start --head` in one process,
    `ray_tpu start --address` in another, driver + state CLI attach over TCP.

    Capability probe (ISSUE 15 deflake, the PR 12 skipif discipline): the
    test boots THREE cold interpreters back to back under 60s/120s
    budgets, and on this 1-core box it fails under ambient load while
    passing 4/4 in isolation (1.2s each — measured in the PR 12 session;
    the tier-1 memory note pins the same flake). When the spin canary
    shows the box contended (<12 Mops vs the ~24-29 idle range of
    BENCH_r06-r08), the interpreter-boot timing would measure the
    NEIGHBORS, not the control plane — skip with the measurement cited.
    An unloaded box still gates at full strength."""
    from conftest import SPIN_CANARY_FLOOR_MOPS, spin_mops

    canary = spin_mops()
    if canary < SPIN_CANARY_FLOOR_MOPS:
        pytest.skip(
            f"box contended (spin canary {canary:.1f} Mops < 12): three "
            "cold-interpreter boots under 60s/120s budgets measure the "
            "ambient load, not the CLI control plane"
        )
    head_proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu", "start", "--head", "--port", "0", "--num-cpus", "0"],
        stdout=subprocess.PIPE,
        text=True,
        env=CLI_ENV,
    )
    node_proc = None
    try:
        line = head_proc.stdout.readline()
        assert "listening on" in line, line
        address = line.strip().rsplit(" ", 1)[-1]
        node_proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu", "start", "--address", address,
             "--num-cpus", "2"],
            stdout=subprocess.PIPE,
            text=True,
            env=CLI_ENV,
        )
        assert "joined" in node_proc.stdout.readline()

        ray_tpu.init(address=address)

        @ray_tpu.remote
        def f(x):
            return x + 1

        assert ray_tpu.get([f.remote(i) for i in range(4)], timeout=60) == [1, 2, 3, 4]
        ray_tpu.shutdown()

        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "summary", "--address", address],
            capture_output=True,
            text=True,
            timeout=120,
            env=CLI_ENV,
        )
        assert out.returncode == 0, out.stderr
        # stray runtime prints (warnings may even CONTAIN braces) can
        # precede the document: the JSON starts at the first bare '{' line
        lines = out.stdout.splitlines()
        summ = json.loads("\n".join(lines[lines.index("{"):]))
        assert summ["tasks"]["by_state"].get("FINISHED", 0) >= 4
        assert len(summ["nodes"]) == 2
    finally:
        for p in (node_proc, head_proc):
            if p is not None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except Exception:
                    p.kill()


def test_system_config_ships_to_agents(monkeypatch):
    """The head sends its non-default config with agent_ack so the
    ``_system_config`` tier reaches remote agent/worker processes (the
    reference's GCS serves system_config to joining raylets). A local
    RAY_TPU_* env var on the agent's host still wins."""
    from ray_tpu._private import config as cfg

    monkeypatch.setattr(cfg.GLOBAL_CONFIG, "node_stats_report_interval_s", 1.25)
    monkeypatch.setattr(cfg.GLOBAL_CONFIG, "object_transfer_chunk_bytes", 65536)
    shipped = cfg.config_overrides()
    assert shipped["node_stats_report_interval_s"] == 1.25
    assert shipped["object_transfer_chunk_bytes"] == 65536

    # receiving side: shipped values apply, except where the operator set env
    monkeypatch.setattr(cfg.GLOBAL_CONFIG, "node_stats_report_interval_s", 5.0)
    monkeypatch.setattr(cfg.GLOBAL_CONFIG, "object_transfer_chunk_bytes", 8 << 20)
    monkeypatch.setenv("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", "1048576")
    cfg.apply_shipped(shipped)
    assert cfg.GLOBAL_CONFIG.node_stats_report_interval_s == 1.25
    assert cfg.GLOBAL_CONFIG.object_transfer_chunk_bytes == 8 << 20  # env wins


def test_shipped_config_reaches_spawned_workers(tcp_cluster, monkeypatch):
    """End to end: an agent forwards head-shipped overrides to the workers
    it spawns, so worker-side knobs follow the driver's _system_config."""
    from ray_tpu._private import config as cfg

    monkeypatch.setattr(cfg.GLOBAL_CONFIG, "streaming_backpressure_items", 5)
    # the fixture's agent registered BEFORE the override: late-joining agents
    # get the current value (registration-time snapshot semantics)
    agent2 = NodeAgent(
        tcp_cluster["address"], resolve_authkey(), resources={"CPU": 1.0, "late": 1.0}
    ).start()
    try:
        assert agent2._config_env.get("RAY_TPU_STREAMING_BACKPRESSURE_ITEMS") == "5"
        ray_tpu.init(address=tcp_cluster["address"])

        @ray_tpu.remote(resources={"late": 1.0})
        def worker_sees():
            from ray_tpu._private.config import GLOBAL_CONFIG

            return GLOBAL_CONFIG.streaming_backpressure_items

        assert ray_tpu.get(worker_sees.remote(), timeout=60) == 5
    finally:
        agent2.shutdown()
