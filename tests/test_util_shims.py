"""util parity shims: multiprocessing.Pool, check_serialize, dashboard CLI.

Reference counterparts: ``ray.util.multiprocessing`` (Pool over tasks),
``ray.util.check_serialize.inspect_serializability``.
"""

import threading

import pytest

import ray_tpu


class TestPool:
    def test_apply_and_map(self, ray_start_regular):
        from ray_tpu.util.multiprocessing import Pool

        with Pool(processes=2) as p:
            assert p.apply(pow, (2, 5)) == 32
            assert p.map(lambda x: x * x, range(8)) == [x * x for x in range(8)]

    def test_starmap_and_async(self, ray_start_regular):
        from ray_tpu.util.multiprocessing import Pool

        with Pool(processes=2) as p:
            assert p.starmap(pow, [(2, 3), (3, 2)]) == [8, 9]
            ar = p.apply_async(pow, (2, 10))
            assert ar.get(timeout=30) == 1024
            assert ar.successful()

    def test_imap_unordered_completes(self, ray_start_regular):
        from ray_tpu.util.multiprocessing import Pool

        with Pool(processes=2) as p:
            out = sorted(p.imap_unordered(lambda x: x + 1, range(6)))
        assert out == list(range(1, 7))

    def test_async_error_propagates(self, ray_start_regular):
        from ray_tpu.util.multiprocessing import Pool

        def boom(x):
            raise RuntimeError("pool-kaboom")

        with Pool(processes=2) as p:
            ar = p.apply_async(boom, (1,))
            with pytest.raises(RuntimeError, match="pool-kaboom"):
                ar.get(timeout=30)
            assert not ar.successful()

    def test_initializer_runs_in_workers(self, ray_start_regular):
        from ray_tpu.util.multiprocessing import Pool

        def setup(v):
            import os

            os.environ["POOL_INIT_FLAG"] = str(v)

        def read(_):
            import os

            return os.environ.get("POOL_INIT_FLAG")

        with Pool(processes=2, initializer=setup, initargs=(7,)) as p:
            assert set(p.map(read, range(4))) == {"7"}


class TestCheckSerialize:
    def test_serializable_object_passes(self):
        from ray_tpu.util.check_serialize import inspect_serializability

        ok, failures = inspect_serializability({"a": [1, 2, 3]})
        assert ok and not failures

    def test_finds_offending_closure_var(self):
        from ray_tpu.util.check_serialize import inspect_serializability

        lock = threading.Lock()  # classic unserializable

        def f():
            return lock

        ok, failures = inspect_serializability(f)
        assert not ok
        assert any(fail.obj is lock for fail in failures)

    def test_finds_offending_attribute(self):
        from ray_tpu.util.check_serialize import inspect_serializability

        class Holder:
            def __init__(self):
                self.fine = 1
                self.bad = threading.Lock()

        ok, failures = inspect_serializability(Holder())
        assert not ok and failures
