"""Autoscaler-lite e2e: infeasible tasks trigger node launches through the
FakeNodeProvider; idle autoscaled nodes are reaped.

Reference: ``python/ray/tests/test_autoscaler_fake_multinode.py``.
"""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import FakeNodeProvider, GKETPUNodeProvider, Monitor, StandardAutoscaler


@pytest.fixture
def cluster(ray_start_cluster):
    c = ray_start_cluster(num_cpus=1)
    c.connect()
    yield c


def test_scale_up_for_infeasible_task(cluster):
    provider = FakeNodeProvider(cluster)
    scaler = StandardAutoscaler(
        provider,
        node_types={"big": {"resources": {"CPU": 4}, "max_workers": 2}},
        idle_timeout_s=1.0,
        launch_grace_s=0.0,
        head=cluster.head,
    )

    @ray_tpu.remote(num_cpus=4)
    def heavy():
        return 42

    ref = heavy.remote()  # infeasible on the 1-CPU head node
    time.sleep(0.2)
    result = scaler.update()
    assert len(result["launched"]) == 1, result
    assert ray_tpu.get(ref, timeout=60) == 42

    # scale-down: node drains, goes idle past the timeout, gets reaped
    deadline = time.time() + 30
    terminated = []
    while time.time() < deadline and not terminated:
        time.sleep(0.3)
        terminated = scaler.update()["terminated"]
    assert terminated, "idle autoscaled node never reaped"
    assert provider.non_terminated_nodes() == []


def test_scale_respects_max_workers(cluster):
    provider = FakeNodeProvider(cluster)
    scaler = StandardAutoscaler(
        provider,
        node_types={"big": {"resources": {"CPU": 2}, "max_workers": 1}},
        idle_timeout_s=60.0,
        head=cluster.head,
    )

    @ray_tpu.remote(num_cpus=2)
    def heavy(i):
        time.sleep(0.5)
        return i

    refs = [heavy.remote(i) for i in range(4)]
    time.sleep(0.2)
    r1 = scaler.update()
    r2 = scaler.update()
    assert len(r1["launched"]) == 1
    assert len(r2["launched"]) == 0  # capped at max_workers=1
    assert ray_tpu.get(refs, timeout=120) == [0, 1, 2, 3]


def test_min_workers_and_monitor(cluster):
    provider = FakeNodeProvider(cluster)
    scaler = StandardAutoscaler(
        provider,
        node_types={"std": {"resources": {"CPU": 2}, "min_workers": 1, "max_workers": 2}},
        idle_timeout_s=60.0,
        head=cluster.head,
    )
    monitor = Monitor(scaler, interval_s=0.1).start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline and not provider.non_terminated_nodes():
            time.sleep(0.1)
        assert len(provider.non_terminated_nodes()) == 1  # min_workers satisfied
    finally:
        monitor.stop()


def test_pending_actor_demand_counts(cluster):
    provider = FakeNodeProvider(cluster)
    scaler = StandardAutoscaler(
        provider,
        node_types={"big": {"resources": {"CPU": 4}, "max_workers": 1}},
        idle_timeout_s=60.0,
        head=cluster.head,
    )

    @ray_tpu.remote(num_cpus=4)
    class Big:
        def ping(self):
            return True

    a = Big.remote()  # pending: no node has 4 CPUs
    time.sleep(0.2)
    result = scaler.update()
    assert len(result["launched"]) == 1
    assert ray_tpu.get(a.ping.remote(), timeout=60)


def test_gke_provider_requires_client():
    p = GKETPUNodeProvider(project="p", zone="z", cluster_name="c")
    with pytest.raises(RuntimeError, match="needs a GKE client"):
        p.create_node("v5e-8", {"TPU": 8}, {})

    class FakeGKE:
        def __init__(self):
            self.n = 0

        def scale_up(self, node_pool, labels):
            self.n += 1
            return f"gke-{node_pool}-{self.n}"

        def delete(self, pid):
            self.n -= 1

    p2 = GKETPUNodeProvider(project="p", zone="z", cluster_name="c", client=FakeGKE())
    pid = p2.create_node("v5e-8", {"TPU": 8}, {})
    assert p2.non_terminated_nodes() == [pid]
    p2.terminate_node(pid)
    assert p2.non_terminated_nodes() == []
