"""Metrics time-series, SLO burn-rate alerting, and the alert plane.

Covers the PR-5 observability tentpole:

* time-series ring: wraparound bounds, counter-reset handling, cross-process
  merge (forward-fill + sum for counters, last-write-wins for gauges);
* burn-rate math golden tests (fast+slow window fire/resolve, flapping
  hysteresis via resolve_after_s);
* span retention caps (bounded deque, dropped-span accounting);
* obs top rate derivation (`—` below 2 samples, delta/dt after);
* serve autoscaler reacting to a firing upscale-labeled alert;
* alert → flight-recorder → `obs alerts` e2e on a LIVE head with synthetic
  TTFT degradation, through FIRING and back to RESOLVED.
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu._private import events as fr
from ray_tpu._private.alerts import FIRING, OK, RESOLVED, AlertManager
from ray_tpu.util import metrics as um
from ray_tpu.util import slo


def _counter_series(samples):
    return {"kind": "counter", "boundaries": None, "series": {"": list(samples)}}


def _gauge_series(samples):
    return {"kind": "gauge", "boundaries": None, "series": {"": list(samples)}}


def _hist(boundaries, per_bucket, s=0.0):
    """buckets+sum+count vector in the metrics layout."""
    return list(per_bucket) + [s, sum(per_bucket)]


# ---------------------------------------------------------------------------
# time-series ring
# ---------------------------------------------------------------------------


class TestSeriesRing:
    def test_wraparound_bounds_memory(self, monkeypatch):
        um._reset_series_for_tests()
        monkeypatch.setenv("RAY_TPU_METRICS_SERIES_CAPACITY", "16")
        g = um.Gauge("t_ring_gauge", "ring test")
        for i in range(50):
            g.set(float(i))
            um.sample_series_now(now=1000.0 + i)
        local = um.get_local_series("t_ring_gauge")
        points = local["t_ring_gauge"]["points"][""]
        assert len(points) == 16  # bounded despite 50 samples
        # drop-oldest: the newest value survives, the oldest are gone
        assert points[-1][1] == 49.0
        assert points[0][1] == 34.0
        um._reset_series_for_tests()

    def test_counter_reset_handling(self):
        # counter restarts from zero mid-window: the post-reset value IS the
        # increase (Prometheus increase() semantics)
        pts = [(0, 100.0), (1, 110.0), (2, 5.0), (3, 10.0)]
        assert um.series_window_delta(pts, 10, now=3) == 10 + 5 + 5
        rates = um.series_rate(pts)
        assert [r for _t, r in rates] == [10.0, 5.0, 5.0]

    def test_latest_rate_requires_two_samples(self):
        assert um.latest_rate([]) is None
        assert um.latest_rate([(0, 5.0)]) is None
        assert um.latest_rate([(0, 5.0), (2, 9.0)]) == pytest.approx(2.0)

    def test_hist_window_delta_and_reset(self):
        b = (0.5, 1.0)
        pts = [
            (0, _hist(b, [10, 0, 0])),
            (5, _hist(b, [20, 5, 0])),
            (6, _hist(b, [1, 1, 0])),  # reset: counts shrank
            (7, _hist(b, [2, 2, 0])),
        ]
        delta = um.hist_window_delta(pts, 100, now=7)
        # 5..0 step: +10/+5; reset step contributes its full vector; last +1/+1
        assert delta[0] == 10 + 1 + 1
        assert delta[1] == 5 + 1 + 1

    def test_merge_forward_fills_counters_across_procs(self):
        now = 1000.0
        raw = {
            "pid-1": {"interval": 1.0, "metrics": {"c": {
                "kind": "counter", "boundaries": None,
                "points": {"": [[now, 10.0], [now + 1, 20.0], [now + 2, 30.0]]},
            }}},
            # pid-2 misses the middle bin: its last value forward-fills
            "pid-2": {"interval": 1.0, "metrics": {"c": {
                "kind": "counter", "boundaries": None,
                "points": {"": [[now, 5.0], [now + 2, 15.0]]},
            }}},
        }
        pts = um.merge_proc_series(raw)["c"]["series"][""]
        assert [v for _t, v in pts] == [15.0, 25.0, 45.0]

    def test_merge_gauges_last_write_wins(self):
        now = 1000.0
        raw = {
            "pid-1": {"interval": 1.0, "metrics": {"g": {
                "kind": "gauge", "boundaries": None,
                "points": {"": [[now + 0.1, 1.0]]},
            }}},
            "pid-2": {"interval": 1.0, "metrics": {"g": {
                "kind": "gauge", "boundaries": None,
                "points": {"": [[now + 0.5, 7.0]]},
            }}},
        }
        pts = um.merge_proc_series(raw)["g"]["series"][""]
        assert pts[-1][1] == 7.0

    def test_series_store_bounded_and_mergeable(self):
        store = um.SeriesStore(capacity=8)
        for i in range(30):
            store.push("pid-9", 1.0, {"c": {
                "kind": "counter", "points": {"": [[100.0 + i, float(i)]]},
            }})
        raw = store.raw()
        assert len(raw["pid-9"]["metrics"]["c"]["points"][""]) == 8
        merged = store.merged()
        assert merged["c"]["kind"] == "counter"

    def test_series_store_push_is_idempotent(self):
        # a push whose reply was lost gets retried in full: the per-proc seq
        # watermark must drop the re-delivered rows instead of duplicating
        store = um.SeriesStore(capacity=32)
        batch = {"c": {"kind": "counter", "points": {
            "": [[1, 100.0, 1.0], [2, 101.0, 2.0]],
        }}}
        store.push("pid-9", 1.0, batch)
        store.push("pid-9", 1.0, batch)  # retry after lost reply
        pts = store.raw()["pid-9"]["metrics"]["c"]["points"][""]
        assert pts == [[100.0, 1.0], [101.0, 2.0]]
        # overlapping retry: old rows dropped, new row lands once
        store.push("pid-9", 1.0, {"c": {"kind": "counter", "points": {
            "": [[2, 101.0, 2.0], [3, 102.0, 5.0]],
        }}})
        pts = store.raw()["pid-9"]["metrics"]["c"]["points"][""]
        assert pts == [[100.0, 1.0], [101.0, 2.0], [102.0, 5.0]]

    def test_ship_then_collect_has_no_duplicates(self):
        # end-to-end: flush() twice in a row (second ship has nothing new)
        # must not duplicate rows in the head store
        um._reset_series_for_tests()
        ray_tpu.init(num_cpus=1, num_tpus=0)
        try:
            g = um.Gauge("t_dedup_gauge", "dedup test")
            g.set(1.0)
            um.sample_series_now(now=1000.0)
            um.flush()
            um.flush()
            um.flush()
            pts = um.collect_series("t_dedup_gauge")["t_dedup_gauge"][
                "series"][""]
            assert len([p for p in pts if p[0] == 1000.0]) == 1
        finally:
            ray_tpu.shutdown()
            um._reset_series_for_tests()

    def test_grafana_slo_panels_track_env_tuned_rules(self, monkeypatch):
        from ray_tpu.util.grafana import _slo_panels

        monkeypatch.setenv("RAY_TPU_SLO_TTFT_THRESHOLD_S", "1.0")
        monkeypatch.setenv("RAY_TPU_SLO_TTFT_OBJECTIVE", "0.999")
        monkeypatch.setenv("RAY_TPU_SLO_FAST_WINDOW_S", "120")
        exprs = {title: expr for title, expr, _u, _d in _slo_panels()}
        ttft = exprs["ttft-p99 fast burn rate"]
        assert 'le="1"' in ttft and "[120s]" in ttft and "/ 0.001" in ttft


# ---------------------------------------------------------------------------
# burn-rate math (golden)
# ---------------------------------------------------------------------------


class TestBurnRate:
    def test_budget_burn_values(self):
        # 1% errors on a 99% objective = exactly burning budget (1.0)
        assert slo.budget_burn(1, 100, 0.99) == pytest.approx(1.0)
        assert slo.budget_burn(50, 100, 0.99) == pytest.approx(50.0)
        assert slo.budget_burn(0, 100, 0.99) == 0.0
        assert slo.budget_burn(5, 0, 0.99) == 0.0  # no traffic, no burn

    def _ttft_rule(self, **kw):
        kw.setdefault("fast_window_s", 60)
        kw.setdefault("slow_window_s", 300)
        kw.setdefault("fast_burn", 14.4)
        kw.setdefault("slow_burn", 6.0)
        return slo.SLORule(
            name="ttft", metric="ttft", kind="histogram_burn",
            objective=0.99, threshold=1.0, **kw,
        )

    def _ttft_series(self, now, fast_bad, fast_good, old_bad, old_good):
        """Two deltas: one landing in both windows (recent) and one only in
        the slow window. Boundaries (0.5, 1.0): bucket 2 (overflow) is bad."""
        b = (0.5, 1.0)
        base = _hist(b, [0, 0, 0])
        old = _hist(b, [0, old_good, old_bad])
        recent = _hist(
            b, [0, old_good + fast_good, old_bad + fast_bad]
        )
        return {
            "ttft": {
                "kind": "histogram", "boundaries": list(b),
                "series": {"": [(now - 280, base), (now - 120, old), (now - 5, recent)]},
            }
        }

    def test_fires_only_when_both_windows_burn(self):
        now = 10_000.0
        rule = self._ttft_rule()
        # fast window burning (50% bad), slow window quiet → no fire
        res = slo.evaluate_rule(
            rule, self._ttft_series(now, fast_bad=50, fast_good=50,
                                    old_bad=0, old_good=1000), now)
        assert res["detail"]["fast_burn"] > rule.fast_burn
        assert not res["breached"]
        # both windows burning → fire
        res = slo.evaluate_rule(
            rule, self._ttft_series(now, fast_bad=50, fast_good=50,
                                    old_bad=50, old_good=50), now)
        assert res["breached"]

    def test_quiet_fast_window_resolves_even_with_slow_residue(self):
        now = 10_000.0
        rule = self._ttft_rule()
        # the outage is old: bad events only in the slow window
        res = slo.evaluate_rule(
            rule, self._ttft_series(now, fast_bad=0, fast_good=100,
                                    old_bad=80, old_good=20), now)
        assert not res["breached"]
        assert res["detail"]["fast_burn"] < rule.fast_burn
        assert res["detail"]["slow_burn"] > rule.slow_burn

    def test_counter_burn_bad_tag_filter(self):
        now = 10_000.0
        rule = slo.SLORule(
            name="err", metric="reqs", kind="counter_burn", objective=0.99,
            bad_tags={"status": "5xx"}, fast_window_s=60, slow_window_s=300,
            fast_burn=14.4, slow_burn=6.0,
        )
        ok_tag = json.dumps({"status": "2xx"})
        bad_tag = json.dumps({"status": "5xx"})
        merged = {"reqs": {"kind": "counter", "boundaries": None, "series": {
            ok_tag: [(now - 280, 0.0), (now - 120, 50.0), (now - 5, 100.0)],
            bad_tag: [(now - 280, 0.0), (now - 120, 50.0), (now - 5, 100.0)],
        }}}
        res = slo.evaluate_rule(rule, merged, now)
        assert res["breached"]  # 50% 5xx in both windows
        merged["reqs"]["series"][bad_tag] = [(now - 280, 0.0), (now - 5, 0.0)]
        assert not slo.evaluate_rule(rule, merged, now)["breached"]

    def test_gauge_threshold_requires_sustained_coverage(self):
        now = 1000.0
        rule = slo.SLORule(
            name="kv", metric="kv", kind="gauge_threshold",
            threshold=0.95, for_s=30.0,
        )
        # spiked 5s ago only: no sample older than the window at threshold
        fresh = _gauge_series([(now - 40, 0.1), (now - 5, 0.99)])
        assert not slo.evaluate_rule(rule, {"kv": fresh}, now)["breached"]
        # pinned for the whole window (and before it)
        pinned = _gauge_series(
            [(now - 45, 0.98), (now - 20, 0.99), (now - 5, 0.99)]
        )
        assert slo.evaluate_rule(rule, {"kv": pinned}, now)["breached"]
        # dipped mid-window → not sustained
        dipped = _gauge_series(
            [(now - 45, 0.98), (now - 20, 0.5), (now - 5, 0.99)]
        )
        assert not slo.evaluate_rule(rule, {"kv": dipped}, now)["breached"]

    def test_no_data_never_breaches(self):
        rule = self._ttft_rule()
        res = slo.evaluate_rule(rule, {}, 1000.0)
        assert not res["breached"] and res["detail"].get("no_data")


# ---------------------------------------------------------------------------
# alert manager state machine
# ---------------------------------------------------------------------------


class TestAlertManager:
    def _rule(self, resolve_after=10.0):
        return slo.SLORule(
            name="g", metric="g", kind="gauge_threshold", threshold=1.0,
            resolve_after_s=resolve_after,
        )

    def test_fire_and_resolve_with_hysteresis(self):
        mgr = AlertManager([self._rule(resolve_after=10.0)])
        hot = {"g": _gauge_series([(99.0, 5.0)])}
        cold = {"g": _gauge_series([(99.0, 0.0)])}
        assert mgr.state()[0]["status"] == OK
        t = mgr.evaluate(hot, now=100.0)
        assert t == [{"rule": "g", "to": FIRING, "value": 5.0}]
        # clean evals inside the hysteresis window do NOT resolve (flapping)
        assert mgr.evaluate(cold, now=104.0) == []
        assert mgr.state()[0]["status"] == FIRING
        # a re-breach resets the clean clock
        assert mgr.evaluate(hot, now=106.0) == []
        assert mgr.evaluate(cold, now=108.0) == []
        assert mgr.evaluate(cold, now=117.0) == []  # only 9s clean
        t = mgr.evaluate(cold, now=119.0)  # 11s clean → resolve
        assert t and t[0]["to"] == RESOLVED
        assert mgr.state()[0]["status"] == RESOLVED

    def test_transitions_land_in_flight_recorder(self):
        fr.clear()
        mgr = AlertManager([self._rule(resolve_after=1.0)])
        mgr.evaluate({"g": _gauge_series([(99.0, 5.0)])}, now=100.0)
        mgr.evaluate({"g": _gauge_series([(99.0, 0.0)])}, now=102.0)
        mgr.evaluate({"g": _gauge_series([(99.0, 0.0)])}, now=104.0)
        types = [e["type"] for e in fr.snapshot() if e["type"].startswith("alert.")]
        assert types == ["alert.fire", "alert.resolve"]

    def test_broken_rule_isolated(self):
        bad = slo.SLORule(name="bad", metric="g", kind="nonsense")
        good = self._rule()
        mgr = AlertManager([bad, good])
        mgr.evaluate({"g": _gauge_series([(99.0, 5.0)])}, now=100.0)
        states = {a["rule"]: a["status"] for a in mgr.state()}
        assert states["g"] == FIRING
        assert states["bad"] == OK
        detail = [a for a in mgr.state() if a["rule"] == "bad"][0]["detail"]
        assert "error" in detail


# ---------------------------------------------------------------------------
# span retention cap
# ---------------------------------------------------------------------------


class TestSpanRetention:
    def test_bounded_with_drop_accounting(self):
        from ray_tpu.util import tracing

        tracing.clear()
        tracing.configure(max_spans=32)
        try:
            before = tracing.span_stats()["dropped"]
            for i in range(100):
                with tracing.span("cap_test", i=i):
                    pass
            stats = tracing.span_stats()
            assert len(tracing.get_spans()) <= 32
            assert stats["dropped"] - before >= 68
            # the newest spans survive (drop-oldest)
            assert tracing.get_spans()[-1]["args"]["i"] == 99
            # the dropped-span counter metric exists and counted
            snap = {m.name: m for m in um._registry}
            assert "tracing_dropped_spans" in snap
        finally:
            tracing.clear()
            tracing.configure(max_spans=tracing._env_max_spans())

    def test_head_sampling_deterministic(self, monkeypatch):
        from ray_tpu.util import tracing

        monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "0.5")
        rid = "00000000deadbeef"  # leading bits 0 → always sampled
        assert tracing.trace_sampled(rid)
        assert tracing.trace_sampled(rid)  # decision is stable
        monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "0")
        assert not tracing.trace_sampled(rid)
        assert tracing.trace_sampled(None)  # context-less spans always kept
        monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "1")
        assert tracing.trace_sampled("ffffffffffffffff")


# ---------------------------------------------------------------------------
# obs top + serve hook
# ---------------------------------------------------------------------------


class TestObsSurfaces:
    def test_series_rate_text_dash_below_two_samples(self):
        from ray_tpu.obs import _series_rate_text

        assert _series_rate_text({}, "x") == "—"
        one = {"x": {"kind": "counter", "series": {"": [(0, 5.0)]}}}
        assert _series_rate_text(one, "x") == "—"
        two = {"x": {"kind": "counter", "series": {"": [(0, 5.0), (2, 9.0)]}}}
        assert _series_rate_text(two, "x") == "2.0"

    def test_render_series_and_alerts_text(self):
        from ray_tpu.obs import render_alerts, render_series

        ent = {"kind": "counter", "boundaries": None,
               "series": {"": [(0, 0.0), (1, 10.0), (2, 30.0)]}}
        text = render_series("c", ent, 60.0)
        assert "last=20.0/s" in text
        text = render_alerts([
            {"rule": "ttft-p99", "status": "FIRING", "value": 20.0,
             "since": time.time() - 5,
             "detail": {"fast_burn": 20.0, "slow_burn": 8.0},
             "labels": {"serve": "upscale"}},
        ])
        assert "FIRING" in text and "serve=upscale" in text

    def test_autoscaler_upscales_on_firing_alert(self):
        from ray_tpu.serve._private.common import AutoscalingConfig
        from ray_tpu.serve._private.controller import desired_replicas

        cfg = AutoscalingConfig(min_replicas=1, max_replicas=5,
                                target_ongoing_requests=100)
        metrics = [{"num_ongoing_requests": 1}]
        assert desired_replicas(cfg, metrics, current=1) == 1
        firing = ({"rule": "ttft-p99", "status": "FIRING",
                   "labels": {"serve": "upscale"}},)
        assert desired_replicas(cfg, metrics, current=1, alerts=firing) == 2
        # non-upscale alerts don't scale
        other = ({"rule": "request-errors", "status": "FIRING",
                  "labels": {"severity": "page"}},)
        assert desired_replicas(cfg, metrics, current=1, alerts=other) == 1


# ---------------------------------------------------------------------------
# e2e on a live head: synthetic TTFT degradation → FIRING → RESOLVED
# ---------------------------------------------------------------------------


class TestAlertsE2E:
    def test_fire_and_resolve_on_live_head(self, monkeypatch):
        monkeypatch.setenv("RAY_TPU_SLO_FAST_WINDOW_S", "2.0")
        monkeypatch.setenv("RAY_TPU_SLO_SLOW_WINDOW_S", "4.0")
        monkeypatch.setenv("RAY_TPU_SLO_RESOLVE_AFTER_S", "0.5")
        monkeypatch.setenv("RAY_TPU_SLO_TTFT_THRESHOLD_S", "0.5")
        monkeypatch.setenv("RAY_TPU_ALERTS_INTERVAL_S", "3600")  # manual ticks
        um._reset_series_for_tests()
        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            from ray_tpu._private.runtime import get_ctx
            from ray_tpu.obs import render_alerts

            ctx = get_ctx()
            h = um.Histogram(
                "llm_time_to_first_token_s", "ttft (e2e synthetic)"
            )
            # baseline sample (one healthy request so the series has a
            # point to diff against), then synthetic degradation: every
            # request blows the 0.5s TTFT bound
            h.observe(0.01)
            um.sample_series_now()
            um.flush()
            for _ in range(50):
                h.observe(5.0)
            um.sample_series_now()
            um.flush()
            alerts = ctx.call("alerts", eval_now=True)
            by_rule = {a["rule"]: a for a in alerts}
            assert by_rule["ttft-p99"]["status"] == "FIRING"
            assert "FIRING" in render_alerts(alerts)
            # the transition reached the flight recorder (head process ring
            # → cluster drain)
            evs = fr.collect_cluster_events()
            fired = [e for e in evs if e.get("type") == "alert.fire"]
            assert any(e.get("rule") == "ttft-p99" for e in fired)
            # recovery: no new bad observations; wait out the fast window
            # plus the hysteresis, shipping fresh (clean) samples meanwhile
            deadline = time.time() + 20
            status = None
            while time.time() < deadline:
                time.sleep(0.5)
                um.sample_series_now()
                um.flush()
                alerts = ctx.call("alerts", eval_now=True)
                status = {a["rule"]: a["status"] for a in alerts}["ttft-p99"]
                if status == "RESOLVED":
                    break
            assert status == "RESOLVED"
            evs = fr.collect_cluster_events()
            assert any(
                e.get("type") == "alert.resolve" and e.get("rule") == "ttft-p99"
                for e in evs
            )
        finally:
            ray_tpu.shutdown()
            um._reset_series_for_tests()

    def test_series_drain_through_head(self, monkeypatch):
        """A worker-side metric's series reaches collect_series() through
        the head store (the cluster-wide drain path obs top uses)."""
        um._reset_series_for_tests()
        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            @ray_tpu.remote
            def bump(n):
                from ray_tpu.util import metrics as wm

                c = wm.Counter("t_drain_counter", "drain test")
                c.inc(n)
                wm.sample_series_now()
                c.inc(n)
                wm.sample_series_now()
                wm.flush()
                return True

            assert ray_tpu.get(bump.remote(7))
            merged = um.collect_series("t_drain_counter")
            pts = merged["t_drain_counter"]["series"][""]
            assert len(pts) >= 2
            assert pts[-1][1] == pytest.approx(14.0)
            assert um.latest_rate(pts) is not None
        finally:
            ray_tpu.shutdown()
            um._reset_series_for_tests()
