"""ray_tpu.train tests — mirrors the reference's train test strategy
(train/tests/test_data_parallel_trainer.py etc.): session plumbing, configs,
checkpointing, failure recovery, and the minimum end-to-end SPMD slice
(SURVEY §7): a pjit MLP trained data-parallel on the 8-device virtual mesh.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


@pytest.fixture
def storage(tmp_path):
    return str(tmp_path / "results")


def test_report_metrics(ray_start_regular, storage):
    def loop(config):
        for i in range(3):
            train.report({"step": i, "loss": 10.0 - i})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t1", storage_path=storage),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3


def test_context_ranks(ray_start_regular, storage):
    def loop():
        ctx = train.get_context()
        train.report({"rank": ctx.get_world_rank(), "world": ctx.get_world_size()})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t2", storage_path=storage),
    )
    result = trainer.fit()
    assert result.metrics["world"] == 2


def test_train_loop_config_passed(ray_start_regular, storage):
    def loop(config):
        train.report({"doubled": config["x"] * 2})

    result = JaxTrainer(
        loop,
        train_loop_config={"x": 21},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t3", storage_path=storage),
    ).fit()
    assert result.metrics["doubled"] == 42


def test_checkpointing_and_keep_n(ray_start_regular, storage, tmp_path):
    def loop(config):
        import tempfile

        for i in range(4):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "state.txt"), "w") as f:
                f.write(str(i))
            train.report({"i": i, "score": float(i)}, checkpoint=Checkpoint.from_directory(d))

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="t4",
            storage_path=storage,
            checkpoint_config=CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="score"
            ),
        ),
    ).fit()
    assert result.checkpoint is not None
    with result.checkpoint.as_directory() as d:
        with open(os.path.join(d, "state.txt")) as f:
            assert f.read() == "3"
    trial_dir = result.path
    kept = [d for d in os.listdir(trial_dir) if d.startswith("checkpoint_")]
    assert len(kept) == 2


def test_worker_failure_restarts_from_checkpoint(ray_start_regular, storage):
    def loop(config):
        import tempfile

        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with ckpt.as_directory() as d:
                with open(os.path.join(d, "step")) as f:
                    start = int(f.read()) + 1
        for i in range(start, 3):
            if i == 1 and start == 0:
                os._exit(1)  # hard crash before step 1 on the first attempt
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "step"), "w") as f:
                f.write(str(i))
            train.report({"step": i, "resumed_at": start}, checkpoint=Checkpoint.from_directory(d))

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="t5",
            storage_path=storage,
            failure_config=FailureConfig(max_failures=1),
        ),
    ).fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert result.metrics["resumed_at"] == 1  # resumed from the step-0 checkpoint


def test_failure_budget_exhausted(ray_start_regular, storage):
    def loop(config):
        os._exit(1)

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t6", storage_path=storage),
    ).fit()
    assert result.error is not None


def test_e2e_pjit_mlp_dp(ray_start_regular, storage):
    """Minimum end-to-end slice: data-parallel pjit training of an MLP over
    the 8-device virtual mesh inside a train worker, with pytree checkpoint
    save + final loss drop (counterpart of the reference's MNIST DDP bench,
    air_benchmarks/workloads/torch_benchmark.py)."""

    def loop(config):
        import tempfile

        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.parallel import MeshConfig, make_mesh
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_mesh(MeshConfig(dp=-1, fsdp=1, tp=1, sp=1))

        key = jax.random.PRNGKey(0)
        w1 = jax.random.normal(key, (16, 64)) * 0.1
        w2 = jax.random.normal(key, (64, 1)) * 0.1
        params = {"w1": w1, "w2": w2}
        opt = optax.adam(1e-2)
        opt_state = opt.init(params)

        def loss_fn(p, x, y):
            h = jnp.tanh(x @ p["w1"])
            pred = h @ p["w2"]
            return jnp.mean((pred - y) ** 2)

        @jax.jit
        def step(p, o, x, y):
            l, g = jax.value_and_grad(loss_fn)(p, x, y)
            up, o = opt.update(g, o)
            return optax.apply_updates(p, up), o, l

        rng = np.random.RandomState(0)
        xs = rng.randn(256, 16).astype(np.float32)
        ys = (xs.sum(axis=1, keepdims=True) * 0.5).astype(np.float32)
        batch_sharding = NamedSharding(mesh, P(("dp", "fsdp")))

        first = last = None
        for e in range(30):
            x = jax.device_put(xs, batch_sharding)
            y = jax.device_put(ys, batch_sharding)
            params, opt_state, l = step(params, opt_state, x, y)
            if first is None:
                first = float(l)
            last = float(l)
        d = tempfile.mkdtemp()
        train.save_pytree(params, d, step=30)
        train.report({"first_loss": first, "loss": last}, checkpoint=Checkpoint.from_directory(d))

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="e2e", storage_path=storage),
    ).fit()
    assert result.error is None
    assert result.metrics["loss"] < result.metrics["first_loss"] * 0.5
    params = train.load_pytree(result.checkpoint)
    assert params["w1"].shape == (16, 64)


def test_dataset_shard_plain_iterable(ray_start_regular, storage):
    def loop(config):
        shard = train.get_dataset_shard("train")
        total = sum(shard)
        train.report({"total": total})

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t7", storage_path=storage),
        datasets={"train": list(range(10))},
    ).fit()
    # each worker sums its round-robin half; rank-0's metrics reported
    assert result.metrics["total"] in (20, 25)
