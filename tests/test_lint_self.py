"""Self-hosting gate: raylint runs clean over ray_tpu itself.

Every violation is either fixed, suppressed inline with a justification, or
recorded in tools/raylint-baseline.json — so any NEW violation introduced by
a PR fails this tier-1 test. Keeping the gate in pytest (not only CI yaml)
means it runs everywhere the test suite runs.
"""

import functools
from pathlib import Path

import ray_tpu
from ray_tpu._lint import baseline as baseline_mod
from ray_tpu._lint import run_paths
from ray_tpu._lint.imports_check import check_imports

PACKAGE_ROOT = Path(ray_tpu.__file__).resolve().parent
BASELINE = PACKAGE_ROOT.parent / "tools" / "raylint-baseline.json"


#: profile of the shared full-package run (the budget test reads it, so
#: the gate costs ONE lint, not two)
_PROFILE: dict = {}


@functools.lru_cache(maxsize=1)
def _all_violations():
    # one full-package lint shared by every test in this module
    return tuple(run_paths([str(PACKAGE_ROOT)], profile=_PROFILE))


def _apply_baseline():
    violations = list(_all_violations())
    if BASELINE.is_file():
        return baseline_mod.apply(violations, baseline_mod.load(BASELINE))
    return violations, 0, []


def test_no_new_lint_violations():
    violations, _, _ = _apply_baseline()
    assert violations == [], (
        "new raylint violations (fix them, suppress with a justified "
        "'# raylint: disable=RLxxx', or — for pre-existing debt only — "
        "regenerate the baseline):\n"
        + "\n".join(v.render() for v in violations)
    )


def test_daemon_loop_fixes_stay_fixed():
    """The PR that introduced raylint fixed RL007 (silent exception
    swallowing) in the head, runtime, node agent and serve controller daemon
    loops. Those files must not regress into the baseline."""
    if not BASELINE.is_file():
        return
    fixed_files = (
        "ray_tpu/_private/head.py",
        "ray_tpu/_private/runtime.py",
        "ray_tpu/_private/node_agent.py",
        "ray_tpu/serve/_private/controller.py",
    )
    entries = baseline_mod.load(BASELINE)
    offenders = [
        fp for fp in entries
        if fp.startswith("RL007:") and any(f in fp for f in fixed_files)
    ]
    assert offenders == [], f"RL007 crept back into fixed files: {offenders}"


def test_full_run_stays_inside_profile_budget():
    """The standing contract (ROADMAP lint gate): the full 24-rule run —
    parse + whole-program index + dataflow rules + the thread/protocol
    phase (RL017-RL019) + the mesh/SPMD phase (RL020-RL024) — finishes
    inside the 30s budget (measured ~8.3s wall at v5 on this container;
    v4 was ~7.5s, so the fifth phase costs well under a second —
    RL020-RL024 together profile at ~45ms). ``--profile`` exposes the
    same numbers on the CLI and CI uploads them (lint-profile artifact),
    so a creeping rule shows up both here and in the trend."""
    _all_violations()  # populates _PROFILE via the shared cached run
    assert _PROFILE, "profile not collected"
    assert _PROFILE["total_s"] < 30.0, _PROFILE
    # every registered rule was actually timed (a rule silently skipped
    # by an import error would otherwise pass the budget trivially)
    assert set(_PROFILE["rules_s"]) >= {f"RL{i:03d}" for i in range(1, 25)}


def test_no_import_cycles():
    problems = check_imports([str(PACKAGE_ROOT)])
    assert problems == [], "\n".join(problems)


def test_baseline_has_no_stale_entries():
    """A baseline entry nothing matches anymore is finished burn-down work:
    delete it (regenerate with --write-baseline) so it cannot mask a future
    regression in the same symbol."""
    if not BASELINE.is_file():
        return
    _, _, stale = _apply_baseline()
    assert stale == [], f"stale baseline entries: {stale}"
