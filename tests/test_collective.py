"""Collective library tests (reference: util/collective tests).

Members are actors; each joins a group and performs the same sequence of
collectives. Host backend only (device plane is covered by parallel tests).
"""

import numpy as np
import pytest

import ray_tpu


@ray_tpu.remote(num_cpus=0)
class Member:
    def __init__(self, rank, world, group="g"):
        from ray_tpu import collective as col

        self.rank = rank
        self.world = world
        self.group = group
        col.init_collective_group(world, rank, group_name=group)

    def do_allreduce(self):
        from ray_tpu import collective as col

        x = np.full((4,), float(self.rank + 1))
        out = col.allreduce(x, group_name=self.group)
        return out

    def do_allgather(self):
        from ray_tpu import collective as col

        return col.allgather(np.array([self.rank]), group_name=self.group)

    def do_reducescatter(self):
        from ray_tpu import collective as col

        x = np.arange(4, dtype=np.float64) + self.rank
        return col.reducescatter(x, group_name=self.group)

    def do_broadcast(self):
        from ray_tpu import collective as col

        x = np.full((3,), float(self.rank * 100))
        return col.broadcast(x, src_rank=1, group_name=self.group)

    def do_sendrecv(self):
        from ray_tpu import collective as col

        if self.rank == 0:
            col.send(np.array([42.0]), dst_rank=1, group_name=self.group)
            return None
        return col.recv(np.zeros(1), src_rank=0, group_name=self.group)

    def do_barrier(self):
        from ray_tpu import collective as col

        col.barrier(group_name=self.group)
        return self.rank

    def rank_info(self):
        from ray_tpu import collective as col

        return col.get_rank(self.group), col.get_collective_group_size(self.group)


@pytest.fixture
def members(ray_start_regular):
    world = 2
    ms = [Member.remote(r, world) for r in range(world)]
    ray_tpu.get([m.rank_info.remote() for m in ms])  # wait for init
    yield ms


def test_allreduce(members):
    outs = ray_tpu.get([m.do_allreduce.remote() for m in members])
    for o in outs:
        np.testing.assert_allclose(o, np.full((4,), 3.0))


def test_allgather(members):
    outs = ray_tpu.get([m.do_allgather.remote() for m in members])
    for o in outs:
        assert [int(x[0]) for x in o] == [0, 1]


def test_reducescatter(members):
    o0, o1 = ray_tpu.get([m.do_reducescatter.remote() for m in members])
    # sum over ranks of arange(4)+r = [1,3,5,7]; rank0 gets [1,3], rank1 [5,7]
    np.testing.assert_allclose(o0, [1.0, 3.0])
    np.testing.assert_allclose(o1, [5.0, 7.0])


def test_broadcast(members):
    outs = ray_tpu.get([m.do_broadcast.remote() for m in members])
    for o in outs:
        np.testing.assert_allclose(o, np.full((3,), 100.0))


def test_send_recv(members):
    outs = ray_tpu.get([m.do_sendrecv.remote() for m in members])
    np.testing.assert_allclose(outs[1], [42.0])


def test_barrier_and_rank(members):
    assert sorted(ray_tpu.get([m.do_barrier.remote() for m in members])) == [0, 1]
    infos = ray_tpu.get([m.rank_info.remote() for m in members])
    assert infos == [(0, 2), (1, 2)]


@ray_tpu.remote(num_cpus=0)
class RingMember:
    """Member driving LARGE allreduces (the chunked-ring path: bulk bytes
    peer-to-peer through the object plane, coordinator shuttles refs only)."""

    def __init__(self, rank, world, group="ring"):
        from ray_tpu import collective as col

        self.rank = rank
        self.world = world
        self.group = group
        col.init_collective_group(world, rank, group_name=group)

    def big_allreduce(self, n):
        import time

        from ray_tpu import collective as col

        x = np.full((n,), float(self.rank + 1), dtype=np.float64)
        t0 = time.perf_counter()
        out = col.allreduce(x, group_name=self.group, timeout=120.0)
        dt = time.perf_counter() - t0
        return float(out[0]), float(out[-1]), dt


def test_ring_allreduce_correct_and_fast(ray_start_regular):
    """VERDICT r2 #7 done-bar: allreduce of 64MB x 8 ranks >= 1 GB/s
    aggregate through the event-driven ring. The full bar only applies on
    hardware that can co-run 8 member processes — this CI VM has ONE core
    (everything timeshares: members' memcpys, the head, the coordinator),
    so the assertion scales with the core count and the measured number is
    printed for the record."""
    import os

    from ray_tpu.collective.collective import _ring_threshold

    world = 8
    n = (64 * 1024 * 1024) // 8  # 64 MB of float64 per rank
    assert n * 8 >= _ring_threshold()  # actually exercises the ring
    members = [RingMember.remote(r, world) for r in range(world)]
    results = ray_tpu.get([m.big_allreduce.remote(n) for m in members], timeout=240)
    expect = float(sum(range(1, world + 1)))
    for first, last, _dt in results:
        assert first == expect and last == expect
    slowest = max(dt for _, _, dt in results)
    aggregate = world * n * 8 / slowest / 1e9
    cores = os.cpu_count() or 1
    # full bar on real hardware; on starved CI (this VM: 1 core for all 8
    # members + head + coordinator) assert only a regression floor that the
    # round-2 polled byte-funnel design would still have to beat
    bar = 1.0 if cores >= 8 else 0.02
    print(f"ring allreduce aggregate: {aggregate:.2f} GB/s ({cores} cores)")
    assert aggregate >= bar, f"aggregate {aggregate:.2f} GB/s below {bar:.2f}"


def test_ring_just_over_threshold(ray_start_regular):
    """The ring path is correct right at its activation boundary (bit-for-
    bit agreement with the direct path is NOT promised — float reduction
    order differs between the two decompositions, as it does in NCCL)."""
    import ray_tpu.collective.collective as cc

    world = 4
    members = [RingMember.options(name=f"rm{r}").remote(r, world, "ring2") for r in range(world)]
    n = cc._ring_threshold() // 8 + 1024  # just over the ring threshold
    results = ray_tpu.get([m.big_allreduce.remote(n) for m in members], timeout=120)
    expect = float(sum(range(1, world + 1)))
    assert all(first == expect and last == expect for first, last, _ in results)


def test_no_client_side_polling():
    """round-2 weakness: 2ms busy-poll helpers. They must be gone — the
    coordinator is an async actor and every wait is an asyncio.Event park."""
    import inspect

    import ray_tpu.collective.collective as cc
    import ray_tpu.collective.coordinator as coord

    assert not hasattr(coord, "poll")
    src = inspect.getsource(coord) + inspect.getsource(cc)
    assert "time.sleep" not in src
    assert "try_collect" not in src
