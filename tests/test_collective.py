"""Collective library tests (reference: util/collective tests).

Members are actors; each joins a group and performs the same sequence of
collectives. Host backend only (device plane is covered by parallel tests).
"""

import numpy as np
import pytest

import ray_tpu


@ray_tpu.remote(num_cpus=0)
class Member:
    def __init__(self, rank, world, group="g"):
        from ray_tpu import collective as col

        self.rank = rank
        self.world = world
        self.group = group
        col.init_collective_group(world, rank, group_name=group)

    def do_allreduce(self):
        from ray_tpu import collective as col

        x = np.full((4,), float(self.rank + 1))
        out = col.allreduce(x, group_name=self.group)
        return out

    def do_allgather(self):
        from ray_tpu import collective as col

        return col.allgather(np.array([self.rank]), group_name=self.group)

    def do_reducescatter(self):
        from ray_tpu import collective as col

        x = np.arange(4, dtype=np.float64) + self.rank
        return col.reducescatter(x, group_name=self.group)

    def do_broadcast(self):
        from ray_tpu import collective as col

        x = np.full((3,), float(self.rank * 100))
        return col.broadcast(x, src_rank=1, group_name=self.group)

    def do_sendrecv(self):
        from ray_tpu import collective as col

        if self.rank == 0:
            col.send(np.array([42.0]), dst_rank=1, group_name=self.group)
            return None
        return col.recv(np.zeros(1), src_rank=0, group_name=self.group)

    def do_barrier(self):
        from ray_tpu import collective as col

        col.barrier(group_name=self.group)
        return self.rank

    def rank_info(self):
        from ray_tpu import collective as col

        return col.get_rank(self.group), col.get_collective_group_size(self.group)


@pytest.fixture
def members(ray_start_regular):
    world = 2
    ms = [Member.remote(r, world) for r in range(world)]
    ray_tpu.get([m.rank_info.remote() for m in ms])  # wait for init
    yield ms


def test_allreduce(members):
    outs = ray_tpu.get([m.do_allreduce.remote() for m in members])
    for o in outs:
        np.testing.assert_allclose(o, np.full((4,), 3.0))


def test_allgather(members):
    outs = ray_tpu.get([m.do_allgather.remote() for m in members])
    for o in outs:
        assert [int(x[0]) for x in o] == [0, 1]


def test_reducescatter(members):
    o0, o1 = ray_tpu.get([m.do_reducescatter.remote() for m in members])
    # sum over ranks of arange(4)+r = [1,3,5,7]; rank0 gets [1,3], rank1 [5,7]
    np.testing.assert_allclose(o0, [1.0, 3.0])
    np.testing.assert_allclose(o1, [5.0, 7.0])


def test_broadcast(members):
    outs = ray_tpu.get([m.do_broadcast.remote() for m in members])
    for o in outs:
        np.testing.assert_allclose(o, np.full((3,), 100.0))


def test_send_recv(members):
    outs = ray_tpu.get([m.do_sendrecv.remote() for m in members])
    np.testing.assert_allclose(outs[1], [42.0])


def test_barrier_and_rank(members):
    assert sorted(ray_tpu.get([m.do_barrier.remote() for m in members])) == [0, 1]
    infos = ray_tpu.get([m.rank_info.remote() for m in members])
    assert infos == [(0, 2), (1, 2)]
