"""Asyncio actors + concurrency groups.

Reference behavior being matched: actors with ``async def`` methods run them
on a per-actor asyncio event loop with high default concurrency
(``python/ray/_raylet.pyx:2082-2084`` — per-concurrency-group asyncio event
loops; ``core_worker/transport/concurrency_group_manager.cc``). Concurrency
groups give named method sets their own concurrency limits
(``@ray.method(concurrency_group="io")``).
"""

import time

import pytest

import ray_tpu


def test_async_methods_run_concurrently(ray_start_regular):
    @ray_tpu.remote
    class Gate:
        def __init__(self):
            self.opened = False

        def open(self):
            self.opened = True

        def is_open(self):
            return self.opened

    @ray_tpu.remote
    class Waiter:
        def __init__(self, gate):
            self.gate = gate

        async def wait_for_gate(self):
            # Polls a second actor: only completes if other coroutines of
            # THIS actor (open_gate) can run while this one is suspended.
            import asyncio

            while not ray_tpu.get(self.gate.is_open.remote()):
                await asyncio.sleep(0.02)
            return "opened"

        async def open_gate(self):
            ray_tpu.get(self.gate.open.remote())
            return "done"

    gate = Gate.remote()
    w = Waiter.remote(gate)
    blocked = w.wait_for_gate.remote()
    opener = w.open_gate.remote()
    assert ray_tpu.get(opener, timeout=10) == "done"
    assert ray_tpu.get(blocked, timeout=10) == "opened"


def test_async_actor_many_overlapping_sleeps(ray_start_regular):
    @ray_tpu.remote
    class A:
        async def nap(self, t):
            import asyncio

            await asyncio.sleep(t)
            return t

    a = A.remote()
    assert ray_tpu.get(a.nap.remote(0.0), timeout=30) == 0.0  # actor warm
    t0 = time.monotonic()
    refs = [a.nap.remote(0.5) for _ in range(10)]
    assert ray_tpu.get(refs, timeout=30) == [0.5] * 10
    # overlapped: 10 x 0.5s naps must beat the 5s serial time comfortably
    assert time.monotonic() - t0 < 3.5


def test_async_max_concurrency_limits(ray_start_regular):
    @ray_tpu.remote(max_concurrency=2)
    class A:
        def __init__(self):
            self.active = 0
            self.peak = 0

        async def work(self):
            import asyncio

            self.active += 1
            self.peak = max(self.peak, self.active)
            await asyncio.sleep(0.2)
            self.active -= 1
            return self.peak

    a = A.remote()
    peaks = ray_tpu.get([a.work.remote() for _ in range(6)], timeout=30)
    assert max(peaks) == 2


def test_concurrency_groups(ray_start_regular):
    @ray_tpu.remote(concurrency_groups={"io": 4, "compute": 1})
    class A:
        def __init__(self):
            self.io_active = 0
            self.io_peak = 0
            self.compute_active = 0
            self.compute_peak = 0

        @ray_tpu.method(concurrency_group="io")
        async def io_task(self):
            import asyncio

            self.io_active += 1
            self.io_peak = max(self.io_peak, self.io_active)
            await asyncio.sleep(0.2)
            self.io_active -= 1

        @ray_tpu.method(concurrency_group="compute")
        async def compute_task(self):
            import asyncio

            self.compute_active += 1
            self.compute_peak = max(self.compute_peak, self.compute_active)
            await asyncio.sleep(0.1)
            self.compute_active -= 1

        async def peaks(self):
            return self.io_peak, self.compute_peak

    a = A.remote()
    refs = [a.io_task.remote() for _ in range(8)]
    refs += [a.compute_task.remote() for _ in range(3)]
    ray_tpu.get(refs, timeout=30)
    io_peak, compute_peak = ray_tpu.get(a.peaks.remote(), timeout=10)
    assert io_peak > 1, "io group should overlap"
    assert io_peak <= 4
    assert compute_peak == 1, "compute group must stay serial"


def test_async_actor_exception_propagates(ray_start_regular):
    @ray_tpu.remote
    class A:
        async def boom(self):
            raise ValueError("async-kaboom")

    a = A.remote()
    with pytest.raises(ValueError, match="async-kaboom"):
        ray_tpu.get(a.boom.remote(), timeout=10)


def test_async_actor_cancel(ray_start_regular):
    @ray_tpu.remote
    class A:
        async def forever(self):
            import asyncio

            while True:
                await asyncio.sleep(0.05)

        async def quick(self):
            return 42

    a = A.remote()
    ref = a.forever.remote()
    time.sleep(0.3)
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.exceptions.TaskCancelledError):
        ray_tpu.get(ref, timeout=10)
    # actor still alive and serving after the cancel
    assert ray_tpu.get(a.quick.remote(), timeout=10) == 42


def test_sync_methods_on_async_actor(ray_start_regular):
    @ray_tpu.remote
    class Mixed:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        async def abump(self):
            self.n += 1
            return self.n

    m = Mixed.remote()
    vals = ray_tpu.get([m.bump.remote(), m.abump.remote(), m.bump.remote()], timeout=10)
    assert sorted(vals) == [1, 2, 3]
