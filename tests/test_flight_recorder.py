"""Flight recorder + end-to-end request tracing (OBSERVABILITY.md).

Coverage demanded by the PR's acceptance criteria:

* trace-context propagation: a ``remote()`` task hop, a nested task hop,
  and an actor-method hop all execute under the submitter's request_id
  (child spans + head task events carry it);
* the recorder ring: bounded wraparound, disable toggle, flush/reload,
  and crash-flush when a worker is SIGTERM'd mid-stream;
* ``prometheus_text()`` re-parses as valid exposition format (cumulative
  histogram buckets, ``le`` labels, ``_sum``/``_count`` consistency);
* bucket-interpolated percentile snapshots (`Histogram.percentiles`,
  `histogram_percentiles`);
* ``obs req`` renders one correlated timeline — proxy → replica →
  engine events under a single request_id, TTFT + per-window accepted
  counts included — from a REAL request served over HTTP through
  ``serve/llm.py`` with ``spec_k > 0``.
"""

import json
import math
import os
import re
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu._private import events
from ray_tpu.util import tracing
from ray_tpu.util.metrics import (
    Counter,
    Gauge,
    Histogram,
    histogram_percentiles,
    percentiles_from_buckets,
    prometheus_text,
)


@pytest.fixture
def fresh_ring():
    """Isolate each test's view of the process-global ring."""
    st = events.stats()
    events.clear()
    events.set_enabled(True)
    yield
    events.configure(capacity=st["capacity"])
    events.set_enabled(st["enabled"])
    events.clear()


# ---------------------------------------------------------------------------
# ring buffer
# ---------------------------------------------------------------------------


class TestRing:
    def test_record_and_snapshot(self, fresh_ring):
        events.record("a.b", request_id="r1", x=1)
        events.record("a.c")
        evs = events.snapshot()
        assert [e["type"] for e in evs] == ["a.b", "a.c"]
        assert evs[0]["request_id"] == "r1" and evs[0]["x"] == 1
        assert "request_id" not in evs[1]
        assert evs[0]["seq"] < evs[1]["seq"]
        assert events.snapshot(request_id="r1") == [evs[0]]

    def test_wraparound_bounds_memory(self, fresh_ring):
        events.configure(capacity=64)
        for i in range(200):
            events.record("w", i=i)
        st = events.stats()
        assert st["size"] == 64 and st["capacity"] == 64
        assert st["dropped"] == 200 - 64
        evs = events.snapshot()
        # the ring keeps the NEWEST 64, oldest first
        assert [e["i"] for e in evs] == list(range(136, 200))

    def test_disable_toggle(self, fresh_ring):
        events.set_enabled(False)
        events.record("nope")
        assert events.snapshot() == []
        events.set_enabled(True)
        events.record("yep")
        assert [e["type"] for e in events.snapshot()] == ["yep"]

    def test_flush_roundtrip(self, fresh_ring, tmp_path):
        events.record("f.one", request_id="rid9", k="v")
        events.record("f.two")
        path = str(tmp_path / "ring.jsonl")
        assert events.flush(path, reason="test") == path
        lines = [json.loads(x) for x in open(path)]
        assert lines[0]["_flight_recorder"] == 1
        assert lines[0]["reason"] == "test" and lines[0]["size"] == 2
        assert [x["type"] for x in lines[1:]] == ["f.one", "f.two"]
        assert lines[1]["request_id"] == "rid9"

    def test_flush_empty_ring_writes_nothing(self, fresh_ring, tmp_path):
        assert events.flush(str(tmp_path / "empty.jsonl")) is None
        assert not (tmp_path / "empty.jsonl").exists()

    def test_recorder_overhead_smoke(self, fresh_ring):
        """The hot path is one lock + tuple append: 50k events must land
        in well under a second even on a loaded CI box (the end-to-end
        ≤5% tokens/s bound is measured by ``llm.bench --smoke`` A/B)."""
        events.configure(capacity=1024)
        t0 = time.perf_counter()
        for i in range(50_000):
            events.record("hot", request_id="r", step=i)
        dt = time.perf_counter() - t0
        assert events.stats()["size"] == 1024
        assert dt < 5.0, f"50k record() took {dt:.2f}s"


def test_crash_flush_on_sigterm_subprocess(tmp_path):
    """A process armed with install_crash_handlers dumps its ring as
    JSONL when SIGTERM kills it (how proc_handles shoots workers)."""
    code = (
        "import os, signal\n"
        "from ray_tpu._private import events\n"
        "events.install_crash_handlers()\n"
        "events.record('boot', request_id='rz', n=1)\n"
        "events.record('work', request_id='rz', n=2)\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
    )
    env = dict(os.environ, RAY_TPU_EVENTS_DIR=str(tmp_path), JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, timeout=60,
        capture_output=True, cwd=os.path.dirname(os.path.dirname(__file__)),
    )
    assert proc.returncode != 0  # died by signal, not a clean exit
    files = [f for f in os.listdir(tmp_path) if f.endswith(".jsonl")]
    assert len(files) == 1, (files, proc.stderr.decode()[-500:])
    lines = [json.loads(x) for x in open(tmp_path / files[0])]
    assert lines[0]["reason"] == "sigterm"
    types = [x["type"] for x in lines[1:]]
    assert types == ["boot", "work", "crash.sigterm"]


def test_worker_killed_mid_stream_leaves_crash_flush(tmp_path, monkeypatch):
    """The acceptance scenario: a worker streaming tokens is SIGTERM'd
    mid-stream; its flight-recorder ring must survive on disk, and the
    offline trace renderer must read it back with the request lane."""
    monkeypatch.setenv("RAY_TPU_EVENTS_DIR", str(tmp_path))
    ray_tpu.init(num_cpus=2, num_tpus=0)
    try:

        @ray_tpu.remote(num_returns="streaming")
        def stream():
            from ray_tpu._private import events as ev
            from ray_tpu.util import tracing as tr

            ev.record("stream.begin", request_id=tr.current_request_id(),
                      pid_hint=os.getpid())
            yield os.getpid()
            for i in range(1000):
                ev.record("stream.tick", request_id=tr.current_request_id(), i=i)
                time.sleep(0.05)
                yield i

        with tracing.trace_context() as rid:
            g = stream.remote()
        it = iter(g)
        victim = ray_tpu.get(next(it), timeout=30)
        ray_tpu.get(next(it), timeout=30)  # producer is inside the loop
        os.kill(victim, signal.SIGTERM)

        deadline = time.time() + 30
        flushed = None
        while time.time() < deadline and flushed is None:
            for f in os.listdir(tmp_path):
                if f == f"events-{victim}.jsonl":
                    flushed = tmp_path / f
            time.sleep(0.2)
        assert flushed is not None, os.listdir(tmp_path)
        lines = [json.loads(x) for x in open(flushed)]
        assert lines[0]["reason"] == "sigterm"
        types = {x["type"] for x in lines[1:]}
        assert "stream.begin" in types and "stream.tick" in types
        # the stream's events carry the submitter's request_id
        assert any(x.get("request_id") == rid for x in lines[1:])

        # postmortem rendering with NO cluster involvement
        from ray_tpu.obs import offline_trace

        out = str(tmp_path / "trace.json")
        entries = offline_trace(str(tmp_path), out)
        lanes = {e["tid"] for e in entries if e.get("pid") == "requests"}
        assert f"req:{rid}" in lanes
        assert json.load(open(out))  # valid chrome-trace JSON
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# trace-context propagation
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_thread_scoping_and_restore(self):
        assert tracing.current_request_id() is None
        with tracing.trace_context("outer123") as rid:
            assert rid == "outer123" == tracing.current_request_id()
            with tracing.trace_context() as inner:
                assert inner != "outer123"
                assert tracing.current_request_id() == inner
            assert tracing.current_request_id() == "outer123"
        assert tracing.current_request_id() is None

    def test_remote_task_hop(self, ray_start_regular):
        @ray_tpu.remote
        def child():
            from ray_tpu.util import tracing as tr

            return tr.current_request_id()

        with tracing.trace_context() as rid:
            ref = child.remote()
        assert ray_tpu.get(ref, timeout=30) == rid

    def test_nested_task_hop(self, ray_start_regular):
        @ray_tpu.remote
        def leaf():
            from ray_tpu.util import tracing as tr

            return tr.current_request_id()

        @ray_tpu.remote
        def mid():
            return ray_tpu.get(leaf.remote(), timeout=30)

        with tracing.trace_context() as rid:
            ref = mid.remote()
        assert ray_tpu.get(ref, timeout=30) == rid

    def test_actor_method_hop(self, ray_start_regular):
        @ray_tpu.remote
        class A:
            def whoami(self):
                from ray_tpu.util import tracing as tr

                return tr.current_request_id()

        a = A.remote()
        with tracing.trace_context() as rid:
            got = ray_tpu.get(a.whoami.remote(), timeout=30)
        assert got == rid
        # a call with NO active context still roots a trace (task-id id)
        rootless = ray_tpu.get(a.whoami.remote(), timeout=30)
        assert rootless and rootless != rid

    def test_child_span_carries_request_id(self, ray_start_regular):
        @ray_tpu.remote
        def spanner():
            from ray_tpu.util import tracing as tr

            with tr.span("child_work", part=1):
                return tr.current_request_id()

        with tracing.trace_context() as rid:
            ray_tpu.get(spanner.remote(), timeout=30)
        spans = [
            s for s in tracing.collect_cluster_spans()
            if s["name"] == "child_work"
            and (s.get("args") or {}).get("request_id") == rid
        ]
        assert spans, "remote span did not inherit the submitter's request_id"

    def test_head_task_events_carry_request_id(self, ray_start_regular):
        from ray_tpu.util import state

        @ray_tpu.remote
        def noop():
            return 1

        with tracing.trace_context() as rid:
            ray_tpu.get(noop.remote(), timeout=30)
        mine = [t for t in state.get_task_events() if t.get("request_id") == rid]
        states = {t["state"] for t in mine}
        assert "FINISHED" in states, "head task events missing the request_id"

    def test_cluster_event_drain(self, ray_start_regular, fresh_ring):
        @ray_tpu.remote
        def emit():
            from ray_tpu._private import events as ev
            from ray_tpu.util import tracing as tr

            ev.record("drain.me", request_id=tr.current_request_id())
            return os.getpid()

        with tracing.trace_context() as rid:
            worker_pid = ray_tpu.get(emit.remote(), timeout=30)
        assert worker_pid != os.getpid()  # really a remote ring
        deadline = time.time() + 20
        got = []
        while time.time() < deadline and not got:
            got = [
                e for e in events.collect_cluster_events(rid)
                if e["type"] == "drain.me"
            ]
        assert got and got[0]["request_id"] == rid


# ---------------------------------------------------------------------------
# metrics: percentiles + prometheus exposition
# ---------------------------------------------------------------------------


def test_percentiles_from_buckets_math():
    bounds = (1.0, 2.0, 4.0)
    counts = (1, 1, 1, 1)  # one obs per bucket incl. overflow
    # rank 2 of 4 lands exactly at the top of bucket[1]
    assert percentiles_from_buckets(bounds, counts, 0.5) == pytest.approx(2.0)
    # deep quantiles clamp at the top finite boundary (overflow bucket)
    assert percentiles_from_buckets(bounds, counts, 0.99) == pytest.approx(4.0)
    # interpolation INSIDE a bucket: all mass in (1, 2]
    assert percentiles_from_buckets(bounds, (0, 10, 0, 0), 0.5) == pytest.approx(1.5)
    assert math.isnan(percentiles_from_buckets(bounds, (0, 0, 0, 0), 0.5))


def test_histogram_percentile_snapshot():
    h = Histogram("fr_pct_hist", "test", boundaries=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    p = h.percentiles()
    assert p["count"] == 4 and p["sum"] == pytest.approx(6.05)
    assert 0.1 < p["p50"] <= 1.0
    assert p["p99"] <= 10.0
    empty = Histogram("fr_pct_empty", "test").percentiles()
    assert empty["count"] == 0 and math.isnan(empty["p50"])


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})? "
    r"(?P<value>[0-9eE+.\-]+|NaN|[+-]Inf)$"
)
_LABEL_RE = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"$')


def _parse_exposition(text: str) -> dict:
    """Minimal Prometheus text-format parser: validates every line and
    returns {family: {"type":..., "samples": [(name, labels, value)]}}."""
    families: dict = {}
    current = None
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram"), line
            current = families.setdefault(name, {"type": kind, "samples": []})
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        labels = {}
        if m.group("labels"):
            for pair in re.split(r",(?=[a-zA-Z_])", m.group("labels")):
                assert _LABEL_RE.match(pair), f"bad label {pair!r} in {line!r}"
                k, v = pair.split("=", 1)
                labels[k] = v[1:-1]
        assert current is not None, f"sample before any TYPE: {line!r}"
        current["samples"].append((m.group("name"), labels, float(m.group("value"))))
    return families


def test_prometheus_text_scrape_and_reparse(ray_start_regular):
    c = Counter("fr_requests_total", "requests served", tag_keys=("route",))
    c.inc(3, tags={"route": "/a"})
    c.inc(2, tags={"route": 'b"quote\\path'})  # exercises label escaping
    g = Gauge("fr_kv_util", "kv utilization")
    g.set(0.375)
    h = Histogram("fr_latency_s", "latency", boundaries=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)

    fams = _parse_exposition(prometheus_text())

    assert fams["ray_tpu_fr_requests_total"]["type"] == "counter"
    by_route = {
        s[1]["route"]: s[2]
        for s in fams["ray_tpu_fr_requests_total"]["samples"]
    }
    assert by_route["/a"] == 3
    # escaped label round-trips: \" -> " and \\ -> "\"
    assert by_route['b\\"quote\\\\path'] == 2

    assert fams["ray_tpu_fr_kv_util"]["samples"][0][2] == 0.375

    hist = fams["ray_tpu_fr_latency_s"]
    assert hist["type"] == "histogram"
    buckets = [(s[1]["le"], s[2]) for s in hist["samples"]
               if s[0].endswith("_bucket")]
    count = [s[2] for s in hist["samples"] if s[0].endswith("_count")][0]
    total = [s[2] for s in hist["samples"] if s[0].endswith("_sum")][0]
    # cumulative and monotone, finite boundaries ordered, +Inf == count
    les = [float("inf") if le == "+Inf" else float(le) for le, _ in buckets]
    assert les == sorted(les) and les[-1] == float("inf")
    vals = [v for _, v in buckets]
    assert vals == sorted(vals)
    assert vals[-1] == count == 5
    assert vals[:3] == [1, 3, 4]  # 0.05 | 0.5,0.5 | 5.0 (50.0 -> +Inf)
    assert total == pytest.approx(56.05)

    # the cluster-merged percentile view exposes the same histogram
    pcts = histogram_percentiles("fr_latency_s")["fr_latency_s"]
    snap = next(iter(pcts.values()))
    assert snap["count"] == 5 and 0.1 <= snap["p50"] <= 1.0


# ---------------------------------------------------------------------------
# obs req: one correlated timeline from a REAL served LLM request
# ---------------------------------------------------------------------------


def test_obs_req_from_served_llm_request():
    """HTTP request → proxy → replica → speculative engine: everything
    correlates under the proxy-minted request_id that comes back in the
    x-request-id response header, and ``obs req`` renders TTFT plus
    per-window accepted-token counts from it."""
    from ray_tpu import serve
    from ray_tpu.llm import EngineConfig
    from ray_tpu.obs import render_request, request_events
    from ray_tpu.serve.llm import build_llm_app

    from ray_tpu.models.gptj import GPTJConfig

    tiny = GPTJConfig(
        vocab_size=128, seq_len=64, d_model=32, n_layers=2, n_heads=2,
        rotary_dim=8, dtype="float32", remat=False, attn_impl="xla",
        fused_loss=False,
    )
    ray_tpu.init(num_cpus=8, num_tpus=0)
    try:
        app = build_llm_app(
            model="gptj",
            model_cfg=tiny,
            engine_config=EngineConfig(
                max_slots=2, num_blocks=32, block_size=4,
                max_blocks_per_seq=12, prefill_chunk=8, spec_k=3,
            ),
        )
        serve.run(app, name="llm", http=True, http_port=0)
        controller = ray_tpu.get_actor("SERVE_CONTROLLER")
        port = ray_tpu.get(controller.get_proxy_port.remote(), timeout=30)

        # periodic prompt: the n-gram drafter finds a match immediately,
        # so at least the first decode window goes through verification
        prompt = [5, 6, 7] * 4
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/llm",
            data=json.dumps(prompt).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            rid = resp.headers.get("x-request-id")
            resp.read()  # drain the stream to completion
        assert rid, "proxy did not return an x-request-id header"

        # the full merged timeline (recorder rings cluster-wide + spans)
        deadline = time.time() + 30
        have = set()
        want = {
            "proxy.request", "replica.request", "llm.submit", "llm.admit",
            "llm.prefill_chunk", "llm.first_token", "llm.verify",
            "llm.finish",
        }
        while time.time() < deadline and not want <= have:
            evs = request_events(rid)
            have = {e["type"] for e in evs}
            time.sleep(0.5)
        assert want <= have, f"missing event types: {want - have}"

        ttfts = [e for e in evs if e["type"] == "llm.first_token"]
        assert ttfts and ttfts[0]["ttft_s"] > 0
        verifies = [e for e in evs if e["type"] == "llm.verify"]
        assert verifies and all(
            0 <= e["accepted"] <= e["proposed"] for e in verifies
        )
        # events are time-ordered: the proxy sees the request before the
        # engine admits it, and the finish comes last of the llm family
        order = [e["type"] for e in evs]
        assert order.index("proxy.request") < order.index("llm.admit")
        assert order.index("llm.admit") < order.index("llm.finish")

        text = render_request(rid, evs)
        assert rid in text and "ttft=" in text and "spec:" in text
        assert "finished: stop" in text or "finished: length" in text

        # chrome trace: one lane per request in the "requests" group
        out = "/tmp/fr_trace_test.json"
        entries = tracing.export_chrome_trace(out)
        lanes = {e["tid"] for e in entries if e.get("pid") == "requests"}
        assert f"req:{rid}" in lanes
        os.remove(out)

        # `x-request-id` passthrough: a caller-supplied id is honored
        req2 = urllib.request.Request(
            f"http://127.0.0.1:{port}/llm",
            data=json.dumps(prompt).encode(),
            headers={
                "Content-Type": "application/json",
                "x-request-id": "caller-chain-0042",
            },
        )
        with urllib.request.urlopen(req2, timeout=300) as resp:
            assert resp.headers.get("x-request-id") == "caller-chain-0042"
            resp.read()
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
