"""GKE provider + cluster YAML + up/down CLI (reference:
``python/ray/autoscaler/_private/gcp/node_provider.py``,
``autoscaler/ray-schema.json``, ``ray up`` commands.py themes)."""

import json
import textwrap

import pytest

from ray_tpu.autoscaler.cluster_config import (
    build_provider,
    load_cluster_config,
    teardown_cluster,
    validate_cluster_config,
)
from ray_tpu.autoscaler.gke import GKEClient, GKETPUAsyncProvider
from ray_tpu.autoscaler.v2 import (
    ALLOCATED,
    RAY_RUNNING,
    REQUESTED,
    TERMINATED,
    AutoscalerV2,
)


class FakeGCP:
    """An http-transport stand-in implementing just enough of the GKE +
    Compute REST surface: node pools with instance groups whose size
    follows setSize; deleteInstances removes named VMs. Every request is
    recorded for assertions."""

    def __init__(self, pools):
        self.pools = {p: [] for p in pools}  # pool -> [vm names]
        self._counter = 0
        self.requests = []
        self.alloc_delay = 0  # extra polls before a resize materializes
        self._pending = []  # (pool, remaining_polls)

    def __call__(self, method, url, body):
        self.requests.append((method, url, body))
        for pool in self.pools:
            if f"/nodePools/{pool}" in url:
                if url.endswith(":setSize"):
                    want = body["nodeCount"]
                    if want > len(self.pools[pool]):
                        for _ in range(want - len(self.pools[pool])):
                            self._pending.append([pool, self.alloc_delay])
                    return {"name": "op-1"}
                return {
                    "name": pool,
                    "initialNodeCount": len(self.pools[pool]),
                    "instanceGroupUrls": [
                        f"https://compute/zones/z/instanceGroups/{pool}-grp"
                    ],
                }
            if f"instanceGroupManagers/{pool}-grp" in url:
                if url.endswith("listManagedInstances"):
                    self._tick(pool)
                    return {
                        "managedInstances": [
                            {"instance": f"https://compute/zones/z/instances/{n}"}
                            for n in self.pools[pool]
                        ]
                    }
                if url.endswith("deleteInstances"):
                    for inst_url in body["instances"]:
                        name = inst_url.rsplit("/", 1)[-1]
                        if name in self.pools[pool]:
                            self.pools[pool].remove(name)
                    return {"name": "op-2"}
        raise AssertionError(f"unexpected request {method} {url}")

    def _tick(self, pool):
        for rec in self._pending:
            if rec[0] == pool:
                if rec[1] <= 0:
                    self._counter += 1
                    self.pools[pool].append(f"{pool}-vm-{self._counter:03d}")
                rec[1] -= 1
        self._pending = [r for r in self._pending if r[1] >= 0]


def _client(fake):
    return GKEClient("proj", "us-central2-b", "clus", http=fake, token_provider=lambda: "t")


def test_gke_client_rest_shapes():
    fake = FakeGCP(["v5e-pool"])
    c = _client(fake)
    c.set_node_pool_size("v5e-pool", 2)
    assert fake.requests[-1][0] == "POST"
    assert fake.requests[-1][1].endswith(
        "projects/proj/zones/us-central2-b/clusters/clus/nodePools/v5e-pool:setSize"
    )
    assert fake.requests[-1][2] == {"nodeCount": 2}
    names = c.list_pool_instances("v5e-pool")
    assert len(names) == 2 and all(n.startswith("v5e-pool-vm-") for n in names)
    c.delete_instance("v5e-pool", names[0])
    assert len(c.list_pool_instances("v5e-pool")) == 1


NODE_TYPES = {
    "v5e-8": {
        "pool": "v5e-pool",
        "resources": {"TPU": 8.0, "CPU": 44.0},
        "labels": {"accelerator": "v5e"},
        "min_workers": 0,
        "max_workers": 4,
    }
}


def _feed_with_nodes(fake, pool, busy=False):
    """Simulate the GKE contract: every VM in the pool has 'joined' ray
    labeled with its VM name as provider_node_id."""
    return {
        "pending_demand": [],
        "nodes": [
            {
                "node_id": f"ray-{n}",
                "labels": {"provider_node_id": n},
                "busy": busy,
            }
            for n in fake.pools[pool]
        ],
    }


def test_gke_provider_scale_up_down_through_v2():
    fake = FakeGCP(["v5e-pool"])
    provider = GKETPUAsyncProvider(pools={"v5e-8": "v5e-pool"}, client=_client(fake))
    feed = {"pending_demand": [{"TPU": 8.0}], "nodes": []}
    scaler = AutoscalerV2(provider, NODE_TYPES, idle_timeout_s=0.0)
    scaler._demand = lambda: feed

    counts = scaler.update()  # demand -> QUEUED -> REQUESTED (resize +1)
    assert counts.get(REQUESTED) == 1
    assert any(u.endswith(":setSize") for _, u, _ in fake.requests)

    counts = scaler.update()  # poll discovers the new VM
    assert counts.get(ALLOCATED) == 1
    inst = next(iter(scaler.im.instances.values()))
    assert inst.provider_id and inst.provider_id.startswith("v5e-pool-vm-")

    feed = _feed_with_nodes(fake, "v5e-pool", busy=True)
    counts = scaler.update()  # the VM's ray node pairs via provider_node_id
    assert counts.get(RAY_RUNNING) == 1
    assert inst.status == RAY_RUNNING and inst.ray_node_id == f"ray-{inst.provider_id}"

    # work done (idle) beyond the (zero) timeout -> precision deleteInstances
    feed = _feed_with_nodes(fake, "v5e-pool", busy=False)
    scaler.update()
    counts = scaler.update()
    assert counts.get(TERMINATED) == 1
    assert fake.pools["v5e-pool"] == []
    assert any(u.endswith("deleteInstances") for _, u, _ in fake.requests)


def test_gke_concurrent_creates_claim_distinct_vms():
    """Two creates in one tick, with ASYNC resizes (alloc_delay>0): the
    second resize must target len+outstanding+1, or it is a no-op and one
    instance polls REQUESTED forever."""
    fake = FakeGCP(["v5e-pool"])
    fake.alloc_delay = 2
    provider = GKETPUAsyncProvider(pools={"v5e-8": "v5e-pool"}, client=_client(fake))
    types = {"v5e-8": dict(NODE_TYPES["v5e-8"], min_workers=2)}
    scaler = AutoscalerV2(provider, types)
    scaler._demand = lambda: {"pending_demand": [], "nodes": []}
    for _ in range(6):
        scaler.update()
    ids = {
        i.provider_id
        for i in scaler.im.instances.values()
        if i.provider_id is not None
    }
    assert len(ids) == 2, f"instances did not claim two distinct VMs: {ids}"
    sizes = [b["nodeCount"] for _, u, b in fake.requests if u.endswith(":setSize")]
    assert sizes == [1, 2], sizes  # second resize accounts for the first


def _yaml(tmp_path, provider="fake", extra=""):
    cfg = textwrap.dedent(
        f"""
        cluster_name: t
        provider:
          type: {provider}
          {"project: p" if provider == "gke_tpu" else ""}
          {"zone: z" if provider == "gke_tpu" else ""}
          {"cluster: c" if provider == "gke_tpu" else ""}
        node_types:
          v5e-8:
            pool: v5e-pool
            resources: {{TPU: 8, CPU: 44}}
            min_workers: 1
            max_workers: 2
        idle_timeout_s: 60
        update_interval_s: 0
        {extra}
        """
    )
    path = tmp_path / "cluster.yaml"
    path.write_text(cfg)
    return str(path)


def test_yaml_schema_validation(tmp_path):
    cfg = load_cluster_config(_yaml(tmp_path))
    assert cfg["cluster_name"] == "t"
    with pytest.raises(ValueError, match="provider.type"):
        validate_cluster_config({"cluster_name": "x", "provider": {"type": "aws"},
                                 "node_types": {"a": {"resources": {}}}})
    with pytest.raises(ValueError, match="missing required"):
        validate_cluster_config({"cluster_name": "x"})
    with pytest.raises(ValueError, match="project"):
        validate_cluster_config(
            {"cluster_name": "x", "provider": {"type": "gke_tpu"},
             "node_types": {"a": {"resources": {"CPU": 1}}}}
        )
    with pytest.raises(ValueError, match="min_workers > max_workers"):
        validate_cluster_config(
            {"cluster_name": "x", "provider": {"type": "fake"},
             "node_types": {"a": {"resources": {"CPU": 1},
                                  "min_workers": 3, "max_workers": 1}}}
        )
    with pytest.raises(ValueError, match="unknown"):
        validate_cluster_config(
            {"cluster_name": "x", "provider": {"type": "fake"}, "typo_key": 1,
             "node_types": {"a": {"resources": {"CPU": 1}}}}
        )


def test_build_provider_gke_pools_map(tmp_path):
    cfg = load_cluster_config(_yaml(tmp_path, provider="gke_tpu"))
    fake = FakeGCP(["v5e-pool"])
    provider = build_provider(cfg, client=_client(fake))
    assert isinstance(provider, GKETPUAsyncProvider)
    assert provider.pools == {"v5e-8": "v5e-pool"}


def test_teardown_deletes_every_pool_vm(tmp_path):
    cfg = load_cluster_config(_yaml(tmp_path, provider="gke_tpu"))
    fake = FakeGCP(["v5e-pool"])
    client = _client(fake)
    client.set_node_pool_size("v5e-pool", 3)
    client.list_pool_instances("v5e-pool")  # materialize
    gone = teardown_cluster(cfg, client=client)
    assert len(gone) == 3
    assert fake.pools["v5e-pool"] == []


def test_up_cli_fake_provider_end_to_end(tmp_path, capsys):
    """`ray_tpu up --ticks N` with the fake provider: head comes up, the
    autoscaler buys min_workers virtual nodes, they join and run."""
    from ray_tpu.scripts import main

    rc = main(["up", _yaml(tmp_path), "--ticks", "4"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "head listening on" in out
    assert "provider_node_id" in out  # the worker-join hint
    counts = json.loads(out.rsplit("instances: ", 1)[1].splitlines()[0])
    assert counts.get("RAY_RUNNING") == 1, counts


def test_down_cli_fake_provider(tmp_path, capsys):
    from ray_tpu.scripts import main

    rc = main(["down", _yaml(tmp_path)])
    assert rc == 0
    assert "terminated 0 instance(s)" in capsys.readouterr().out
