"""Worker forkserver template + idle-worker adoption (VERDICT r4 #2).

Reference: the raylet's pre-started worker pool
(``src/ray/raylet/worker_pool.h:152``) exists so leases never pay
interpreter boot; the TPU build's answer is a per-node warm template every
worker forks from (``_private/worker_template.py``) plus actor adoption of
idle pool workers. The spawn-rate target comes from the 40k-actor
scalability envelope (``release/benchmarks/README.md:12``).
"""

import time

import pytest

import ray_tpu
from ray_tpu._private.config import GLOBAL_CONFIG


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


def _head():
    from ray_tpu._private.runtime import get_ctx

    return get_ctx().head


def test_template_forks_workers(cluster):
    @ray_tpu.remote(num_cpus=0)
    class A:
        def pid(self):
            import os

            return os.getpid()

    actors = [A.remote() for _ in range(8)]
    pids = ray_tpu.get([a.pid.remote() for a in actors], timeout=120)
    assert len(set(pids)) == 8
    h = _head()
    node = next(iter(h.nodes.values()))
    assert node.template is not None and node.template.alive()
    # every dedicated actor worker either forked from the template or was
    # adopted from the pool — no cold Popen spawns on the default env path
    forked = [w for w in node.all_workers if w.alive and w.actor_id is not None]
    assert forked and all(w.forked or w.proc is None or not hasattr(w.proc, "popen") for w in forked)
    for a in actors:
        ray_tpu.kill(a)


def test_forked_worker_runs_plain_tasks(cluster):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get([f.remote(i) for i in range(50)], timeout=120) == list(
        range(1, 51)
    )


# the spin canary lives in conftest (shared with test_multihost's CLI
# roundtrip probe) so the contention threshold is tuned in ONE place


# tier-1 budget (ISSUE 13): 24.8s measured on the dev box — and the
# 100-actor wave's registration timing flaked the same run; the wave is
# a scale probe, not a correctness gate, so it rides the slow tier
@pytest.mark.slow
def test_spawn_wave_no_registration_respawns(cluster):
    """A 100-actor wave must complete without a single registration-timeout
    respawn (r4: the wave drowned in 30s-timeout retry loops).

    Load tolerance (ISSUE 14 deflake): the PR 13 full-suite timing run
    flaked this wave under `-m slow` load — the 30s registration window
    and the rate floor were measuring the NEIGHBORS, not the spawn path.
    When the assertions fail AND the spin canary shows the box is
    contended (this box idles at ~24-29 Mops across BENCH_r06-r08; a
    saturated run measured <10), skip with the measurement cited instead
    of failing; an unloaded box still gates at full strength."""

    @ray_tpu.remote(num_cpus=0)
    class E:
        def ping(self):
            return 1

    t0 = time.monotonic()
    wave = [E.remote() for _ in range(100)]
    assert ray_tpu.get([x.ping.remote() for x in wave], timeout=300) == [1] * 100
    dt = time.monotonic() - t0
    h = _head()
    node = next(iter(h.nodes.values()))
    retried = [
        w for w in node.all_workers if w.actor_id is not None and w.spawn_attempts > 0
    ]
    rate = 100 / dt
    if retried or rate <= 5:
        from conftest import SPIN_CANARY_FLOOR_MOPS, spin_mops

        canary = spin_mops()
        if canary < SPIN_CANARY_FLOOR_MOPS:
            pytest.skip(
                f"box contended (spin canary {canary:.1f} Mops < 12): wave "
                f"{rate:.1f}/s with {len(retried)} registration respawns is "
                "ambient load, not a spawn-path regression"
            )
    assert not retried, f"{len(retried)} workers hit the registration-timeout respawn"
    # spawn-rate floor: generous vs the >=20/s target so a loaded CI box
    # doesn't flake, but far above r4's 0.88/s
    assert rate > 5, f"spawn wave too slow: {rate:.1f}/s"
    for x in wave:
        ray_tpu.kill(x)


def test_actor_adopts_idle_pool_worker(cluster):
    @ray_tpu.remote
    def warm():
        import os

        return os.getpid()

    pool_pid = ray_tpu.get(warm.remote(), timeout=60)
    h = _head()
    node = next(iter(h.nodes.values()))
    assert node.idle_workers, "expected an idle pool worker after the task"
    n_workers = len([w for w in node.all_workers if w.alive])

    @ray_tpu.remote(num_cpus=0)
    class A:
        def pid(self):
            import os

            return os.getpid()

    a = A.remote()
    actor_pid = ray_tpu.get(a.pid.remote(), timeout=60)
    # the actor took over the idle pool worker: same process, no new spawn
    assert actor_pid == pool_pid
    assert len([w for w in node.all_workers if w.alive]) == n_workers
    ray_tpu.kill(a)


def test_forkserver_disabled_falls_back(cluster_off=None):
    old = GLOBAL_CONFIG.worker_forkserver_enabled
    GLOBAL_CONFIG.worker_forkserver_enabled = False
    try:
        ray_tpu.init(num_cpus=2, num_tpus=0)

        @ray_tpu.remote
        def f():
            return 42

        assert ray_tpu.get(f.remote(), timeout=120) == 42
        from ray_tpu._private.runtime import get_ctx

        node = next(iter(get_ctx().head.nodes.values()))
        assert node.template is None
        assert all(not w.forked for w in node.all_workers)
    finally:
        GLOBAL_CONFIG.worker_forkserver_enabled = old
        ray_tpu.shutdown()


@pytest.mark.slow
def test_envelope_1k_actors():
    """Scalability envelope: 1000 concurrent trivial actors on one node
    (reference envelope: 40k actors across 2000 nodes — this is the
    single-node slice, bounded for CI)."""
    ray_tpu.init(num_cpus=4, num_tpus=0)
    try:
        @ray_tpu.remote(num_cpus=0)
        class E:
            def ping(self):
                return 1

        wave = [E.remote() for _ in range(1000)]
        out = ray_tpu.get([x.ping.remote() for x in wave], timeout=900)
        assert out == [1] * 1000
        for x in wave:
            ray_tpu.kill(x)
    finally:
        ray_tpu.shutdown()
