"""PB2 scheduler + Optuna adapter tests (reference themes:
``tune/tests/test_schedulers_pbt.py`` PB2 cases, ``test_searchers.py``)."""

import math
import os
import tempfile

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.tune.pb2 import PB2
from ray_tpu.tune.schedulers import EXPLOIT, PopulationBasedTraining


class _Trial:
    def __init__(self, tid, config):
        self.id = tid
        self.config = dict(config)
        self.score = 0.0
        self.checkpoint = object()  # truthy: controller requires one to exploit


def _rate(lr):
    """Reward earned per step as a function of lr; peak at lr=1e-2."""
    return max(0.0, 1.0 - (math.log10(lr) + 2.0) ** 2)


def _simulate(sched, seed, n_trials=4, steps=48):
    """Drive the controller's scheduler contract directly: per-step results,
    EXPLOIT -> choose_exploit_source -> perturb_config + state clone.
    Returns total reward accumulated by the population (cumulative regret
    proxy — the quantity PB2's bandit formulation actually optimizes)."""
    import random

    rng = random.Random(seed)
    trials = [
        _Trial(f"t{i}", {"lr": 10 ** rng.uniform(-6, 0)}) for i in range(n_trials)
    ]
    total = 0.0
    for step in range(1, steps + 1):
        for tr in trials:
            r = _rate(tr.config["lr"])
            tr.score += r
            total += r
            decision = sched.on_result(
                tr, {"reward": tr.score, "training_iteration": step}
            )
            if decision == EXPLOIT:
                donor = sched.choose_exploit_source(tr, trials)
                if donor is not None:
                    tr.config = sched.perturb_config(dict(donor.config))
                    tr.score = donor.score
    return total


def test_pb2_gp_receives_observations():
    """Regression: the observation windows must actually close — PBT fires
    EXPLOIT every interval, one report earlier than a naive `>= interval`
    window close can trigger, which once starved the GP to zero data."""
    sched = PB2(
        metric="reward",
        mode="max",
        perturbation_interval=2,
        hyperparam_bounds={"lr": (1e-6, 1.0)},
        seed=0,
    )
    _simulate(sched, seed=0, n_trials=4, steps=20)
    assert len(sched._y) >= 20, f"GP starved: only {len(sched._y)} observations"


def test_pb2_beats_random_perturbation():
    """The GP-UCB explore step must earn more cumulative reward than PBT's
    random multiply, given identical exploit machinery (seeded, 3 seeds)."""
    seeds = [0, 1, 2]
    pb2_total = sum(
        _simulate(
            PB2(
                metric="reward",
                mode="max",
                perturbation_interval=2,
                hyperparam_bounds={"lr": (1e-6, 1.0)},
                seed=s,
            ),
            seed=s,
        )
        for s in seeds
    )
    pbt_total = sum(
        _simulate(
            PopulationBasedTraining(
                metric="reward",
                mode="max",
                perturbation_interval=2,
                hyperparam_mutations={"lr": tune.loguniform(1e-6, 1.0)},
                seed=s,
            ),
            seed=s,
        )
        for s in seeds
    )
    assert pb2_total > pbt_total, (pb2_total, pbt_total)


def test_pb2_respects_bounds_and_log_detection():
    sched = PB2(
        metric="r",
        mode="max",
        hyperparam_bounds={"lr": (1e-5, 1.0), "mom": (0.8, 0.99)},
        seed=1,
    )
    assert sched._log_key["lr"] and not sched._log_key["mom"]
    # encode/decode round-trips inside bounds
    cfg = {"lr": 3e-3, "mom": 0.9}
    dec = sched._decode(sched._encode(cfg))
    assert dec["lr"] == pytest.approx(3e-3, rel=1e-6)
    assert dec["mom"] == pytest.approx(0.9, rel=1e-6)
    # perturbations stay in bounds, with and without GP data
    for trial_i in range(30):
        out = sched.perturb_config({"lr": 1e-3, "mom": 0.95, "batch": 32})
        assert 1e-5 <= out["lr"] <= 1.0
        assert 0.8 <= out["mom"] <= 0.99
        assert out["batch"] == 32  # unbounded keys ride along unchanged
        tr = _Trial(f"t{trial_i}", out)
        sched.on_result(tr, {"r": 0.0, "training_iteration": 0})
        sched.on_result(tr, {"r": float(trial_i % 5), "training_iteration": 2})


def test_pb2_requires_bounds():
    with pytest.raises(ValueError):
        PB2(metric="r", mode="max")


def test_pb2_end_to_end_tuner(ray_start_regular, tmp_path):
    """PB2 plugs into the Tuner exactly where PBT does."""

    def trainable(config):
        level = 0.0
        ckpt = tune.get_checkpoint()
        if ckpt:
            with ckpt.as_directory() as d:
                with open(os.path.join(d, "lvl")) as f:
                    level = float(f.read())
        import math as _m

        for _ in range(6):
            level += max(0.0, 1.0 - (_m.log10(config["lr"]) + 2.0) ** 2)
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "lvl"), "w") as f:
                f.write(str(level))
            tune.report({"reward": level}, checkpoint=Checkpoint.from_directory(d))

    pb2 = PB2(
        metric="reward",
        mode="max",
        perturbation_interval=2,
        hyperparam_bounds={"lr": (1e-4, 1.0)},
        seed=0,
    )
    grid = tune.run(
        trainable,
        config={"lr": tune.grid_search([1e-4, 1e-3, 1e-1, 1.0])},
        metric="reward",
        mode="max",
        scheduler=pb2,
        storage_path=str(tmp_path),
        name="pb2",
    )
    assert len(grid) == 4
    assert grid.get_best_result().metrics["reward"] > 0.5


def test_optuna_searcher_adapter():
    pytest.importorskip("optuna")
    from ray_tpu.tune.optuna_adapter import OptunaSearcher

    searcher = OptunaSearcher(metric="loss", mode="min", seed=0)
    searcher.set_search_properties(
        "loss",
        "min",
        {
            "x": tune.uniform(-10, 10),
            "depth": tune.randint(1, 5),
            "act": tune.choice(["relu", "gelu"]),
            "const": 7,
        },
    )
    best = math.inf
    for i in range(40):
        cfg = searcher.suggest(f"t{i}")
        assert 1 <= cfg["depth"] <= 4 and cfg["act"] in ("relu", "gelu")
        assert cfg["const"] == 7
        loss = (cfg["x"] - 3.0) ** 2 + 0.1 * cfg["depth"]
        best = min(best, loss)
        searcher.on_trial_complete(f"t{i}", {"loss": loss})
    assert best < 1.0, f"optuna TPE did not converge: {best}"


def test_optuna_import_error_message():
    try:
        import optuna  # noqa: F401

        pytest.skip("optuna installed; error path not reachable")
    except ImportError:
        pass
    from ray_tpu.tune.optuna_adapter import OptunaSearcher

    with pytest.raises(ImportError, match="optuna"):
        OptunaSearcher(metric="loss", mode="min")
