"""Workflow depth: continuations (dynamic workflows), durable events,
virtual actors (reference: ``python/ray/workflow`` recursion/
``wait_for_event``/virtual-actor themes)."""

import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu import workflow


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def fib_step(n, acc_prev, acc):
    """Returns a continuation until n hits 0 — recursion via dynamic DAGs."""
    if n == 0:
        return acc
    return workflow.continuation(fib_step.bind(n - 1, acc, acc_prev + acc))


def test_continuation_recursion(ray_start_regular, tmp_path):
    out = workflow.run(
        fib_step.bind(8, 0, 1), workflow_id="fib", storage=str(tmp_path)
    )
    assert out == 34  # fib(9)
    # sub-steps checkpointed under the parent step's namespace
    events = workflow.get_events("fib", str(tmp_path))
    assert any(e["type"] == "continuation_started" for e in events)


def test_continuation_resume_skips_done_rounds(ray_start_regular, tmp_path):
    marker = tmp_path / "ran"

    @ray_tpu.remote
    def outer():
        return workflow.continuation(inner.bind())

    @ray_tpu.remote
    def inner():
        with open(marker, "a") as f:
            f.write("x")
        return "done"

    assert workflow.run(outer.bind(), workflow_id="c1", storage=str(tmp_path)) == "done"
    assert workflow.resume("c1", storage=str(tmp_path)) == "done"
    assert marker.read_text() == "x"  # the inner step ran exactly once


def test_continuation_mid_dag_fails_loudly(ray_start_regular, tmp_path):
    """Continuations are tail-position only: a step with downstream
    consumers returning one must fail the workflow with a clear error, not
    feed the raw Continuation object onward."""

    @ray_tpu.remote
    def sneaky():
        return workflow.continuation(add.bind(1, 2))

    dag = add.bind(sneaky.bind(), 10)
    with pytest.raises(Exception, match="tail-position|Continuation"):
        workflow.run(dag, workflow_id="midc", storage=str(tmp_path))


def test_wait_for_event_delivery(ray_start_regular, tmp_path):
    ev = workflow.wait_for_event("go", timeout_s=30)
    dag = add.bind(ev, 10)

    def deliver():
        time.sleep(0.5)
        workflow.send_event("evt1", "go", 32, storage=str(tmp_path))

    t = threading.Thread(target=deliver)
    t.start()
    out = workflow.run(dag, workflow_id="evt1", storage=str(tmp_path))
    t.join()
    assert out == 42
    # delivered payload is durable: a resume never waits again
    assert workflow.resume("evt1", storage=str(tmp_path)) == 42


def test_wait_for_event_timeout(ray_start_regular, tmp_path):
    dag = add.bind(workflow.wait_for_event("never", timeout_s=0.3), 1)
    with pytest.raises(Exception, match="never"):
        workflow.run(dag, workflow_id="evt2", storage=str(tmp_path))


def test_virtual_actor_durable_state(ray_start_regular, tmp_path):
    @workflow.virtual_actor
    class Counter:
        def __init__(self, start=0):
            self.value = start

        def incr(self, by=1):
            self.value += by
            return self.value

        @workflow.readonly
        def peek(self):
            return self.value

    c = Counter.get_or_create("c1", 5, storage=str(tmp_path))
    assert c.incr() == 6
    assert c.incr(4) == 10
    assert c.peek() == 10

    # a fresh handle (fresh process in real life) sees the committed state
    again = Counter.get_or_create("c1", 999, storage=str(tmp_path))
    assert again.peek() == 10  # get_or_create never re-inits an existing actor

    attached = workflow.get_actor("c1", Counter, storage=str(tmp_path))
    assert attached.incr() == 11

    with pytest.raises(ValueError):
        workflow.get_actor("missing", Counter, storage=str(tmp_path))


def test_virtual_actor_readonly_commits_nothing(ray_start_regular, tmp_path):
    @workflow.virtual_actor
    class Box:
        def __init__(self):
            self.v = 1

        @workflow.readonly
        def sneaky(self):
            self.v = 99  # mutation in a readonly method must NOT persist
            return self.v

        @workflow.readonly
        def peek(self):
            return self.v

    b = Box.get_or_create("b1", storage=str(tmp_path))
    assert b.sneaky() == 99
    assert b.peek() == 1


def test_virtual_actor_head_mutex(ray_start_regular, tmp_path):
    """Transactions serialize on the head-side named mutex (VERDICT r4
    weak #8: the fcntl lock degraded on networked storage); a crashed
    holder's lease expires instead of wedging the actor forever."""
    from ray_tpu._private.runtime import get_ctx

    @workflow.virtual_actor
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.get_or_create("mtx", storage=str(tmp_path))
    assert c.bump() == 1

    ctx = get_ctx()
    # concurrent writers from threads interleave cleanly through the mutex
    import threading

    results = []

    def writer():
        h = workflow.get_actor("mtx", Counter, storage=str(tmp_path))
        results.append(h.bump())

    ts = [threading.Thread(target=writer) for _ in range(4)]
    [t.start() for t in ts]
    [t.join(timeout=60) for t in ts]
    assert sorted(results) == [2, 3, 4, 5]  # no lost updates

    # crashed holder: acquire the actor's mutex with a short lease and
    # never release — the next transaction proceeds after expiry
    name = c._mutex_key()  # storage-independent UUID identity
    assert ctx.call("mutex_acquire", name=name, owner="dead-client", lease_s=0.5)
    t0 = time.monotonic()
    assert c.bump() == 6
    assert time.monotonic() - t0 >= 0.3  # actually waited for the lease
