"""Object-plane durability: spill at the shm watermark, transparent restore,
eviction of dropped objects.

Reference: ``src/ray/raylet/local_object_manager.h:41-76`` (spill/restore/
delete of primary copies), plasma LRU eviction.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.runtime import get_ctx


MB = 1024 * 1024


@pytest.fixture
def spill_cluster():
    # watermark 32MB; each test object is ~8MB
    ray_tpu.init(
        num_cpus=4, _system_config={"object_spilling_threshold_bytes": 32 * MB}
    )
    yield
    ray_tpu.shutdown()


def _head():
    return get_ctx().head


def test_spill_beyond_capacity_round_trips(spill_cluster):
    arrays = [np.full(MB, i, np.float64) for i in range(10)]  # 10 × 8MB = 80MB
    refs = [ray_tpu.put(a) for a in arrays]
    head = _head()
    assert head.shm_owner.bytes_used <= 40 * MB  # spilled below watermark-ish
    with head.lock:
        spilled = [e for e in head.objects.values() if e.spill_path is not None]
    assert spilled, "nothing spilled despite 2.5x capacity"
    # every object restores transparently and matches
    for i, r in enumerate(refs):
        out = ray_tpu.get(r, timeout=60)
        np.testing.assert_array_equal(out, arrays[i])


def test_spilled_object_feeds_task_args(spill_cluster):
    refs = [ray_tpu.put(np.full(MB, i, np.float64)) for i in range(8)]

    @ray_tpu.remote
    def mean(x):
        return float(x.mean())

    assert ray_tpu.get([mean.remote(r) for r in refs], timeout=120) == [
        float(i) for i in range(8)
    ]


def test_dropped_refs_evict_shm_and_spill_files(spill_cluster):
    head = _head()
    refs = [ray_tpu.put(np.zeros(MB, np.float64)) for _ in range(6)]
    with head.lock:
        n_before = len(head.objects)
    assert n_before >= 6
    del refs
    deadline = time.time() + 20
    while time.time() < deadline:
        with head.lock:
            if len(head.objects) < n_before - 4:
                break
        time.sleep(0.2)
    with head.lock:
        remaining = len(head.objects)
    assert remaining < n_before - 4, f"objects not evicted: {remaining}/{n_before}"


def test_spill_skips_pinned_inflight_args(spill_cluster):
    """An object pinned as a pending task's arg must not lose its shm copy
    mid-dispatch."""

    @ray_tpu.remote
    def slow_consume(x, delay):
        time.sleep(delay)
        return float(x.sum())

    pinned = ray_tpu.put(np.ones(MB, np.float64))
    fut = slow_consume.remote(pinned, 0.5)
    # flood the store to force spill pressure while the task holds its pin
    extra = [ray_tpu.put(np.zeros(MB, np.float64)) for _ in range(8)]
    assert ray_tpu.get(fut, timeout=120) == float(8 * MB / 8)
    del extra


def test_borrowed_refs_release_on_drop(spill_cluster):
    """A ref that crossed serialization boundaries (returned inside another
    object) no longer pins its target forever: when every holder drops, the
    object evicts (reference: borrower refcounting,
    ``core_worker/reference_count.h:61-115``)."""
    import numpy as np

    head = _head()

    @ray_tpu.remote
    def make_nested():
        inner = ray_tpu.put(np.ones(512 * 1024, np.float64))  # 4MB
        return {"payload": inner}

    outer = make_nested.remote()
    nested = ray_tpu.get(outer, timeout=60)
    inner_ref = nested["payload"]
    inner_id = inner_ref.binary()
    np.testing.assert_array_equal(
        ray_tpu.get(inner_ref, timeout=60), np.ones(512 * 1024, np.float64)
    )
    with head.lock:
        assert inner_id in head.objects
    # drop every holder: outer object ref, the deserialized inner ref
    del outer, nested, inner_ref
    deadline = time.time() + 20
    while time.time() < deadline:
        with head.lock:
            if inner_id not in head.objects:
                break
        time.sleep(0.2)
    with head.lock:
        assert inner_id not in head.objects, "borrowed ref leaked after all holders dropped"
