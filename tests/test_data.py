"""ray_tpu.data tests.

Models the reference's ``python/ray/data/tests`` coverage: block ops,
transformations + fusion, all-to-all exchanges, datasources, iteration
(incl. device batches), splits, groupby, writes.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data.block import BlockAccessor


def test_range_count_take(ray_start_regular):
    ds = rd.range(100, parallelism=5)
    assert ds.count() == 100
    assert ds.take(3) == [{"id": 0}, {"id": 1}, {"id": 2}]
    assert len(ds.take_all()) == 100


def test_map_batches_fusion_and_formats(ray_start_regular):
    ds = rd.range(64, parallelism=4)
    out = (
        ds.map_batches(lambda b: {"id": b["id"] * 2})
        .map_batches(lambda b: {"id": b["id"] + 1})
        .sum("id")
    )
    assert out == sum(2 * i + 1 for i in range(64))
    # pandas format
    def pdf(df):
        df["id"] = df["id"] * 3
        return df

    assert rd.range(10).map_batches(pdf, batch_format="pandas").sum("id") == 3 * 45
    # pyarrow format passthrough
    assert rd.range(10).map_batches(lambda t: t, batch_format="pyarrow").count() == 10


def test_map_batches_batch_size_rebatching(ray_start_regular):
    seen = []

    def record(b):
        seen.append(len(b["id"]))
        return b

    ds = rd.range(100, parallelism=7).map_batches(record, batch_size=32)
    assert ds.count() == 100


def test_map_filter_flatmap(ray_start_regular):
    ds = rd.range(20, parallelism=3)
    assert ds.map(lambda r: {"x": r["id"] ** 2}).take(3) == [{"x": 0}, {"x": 1}, {"x": 4}]
    assert ds.filter(lambda r: r["id"] < 5).count() == 5
    assert ds.flat_map(lambda r: [{"y": r["id"]}, {"y": -r["id"]}]).count() == 40


def test_column_ops(ray_start_regular):
    ds = rd.range(10).add_column("double", lambda b: b["id"] * 2)
    row = ds.take(1)[0]
    assert row == {"id": 0, "double": 0}
    assert ds.select_columns(["double"]).columns() == ["double"]
    assert ds.drop_columns(["double"]).columns() == ["id"]
    assert ds.rename_columns({"id": "idx"}).columns() == ["idx", "double"]


def test_limit_early_stop(ray_start_regular):
    ds = rd.range(10_000, parallelism=16).limit(25)
    rows = ds.take_all()
    assert [r["id"] for r in rows] == list(range(25))


def test_sort_shuffle_repartition(ray_start_regular):
    ds = rd.range(200, parallelism=8)
    got = [r["id"] for r in ds.sort("id", descending=True).take_all()]
    assert got == sorted(range(200), reverse=True)
    shuffled = [r["id"] for r in ds.random_shuffle(seed=42).take_all()]
    assert shuffled != list(range(200)) and sorted(shuffled) == list(range(200))
    assert ds.repartition(5).num_blocks() == 5


def test_union_zip(ray_start_regular):
    a = rd.range(10, parallelism=2)
    b = rd.range(10, parallelism=2).map(lambda r: {"id": r["id"] + 10})
    assert a.union(b).count() == 20
    z = a.zip(rd.range(10, parallelism=3).map(lambda r: {"v": r["id"] * 2}))
    rows = sorted(z.take_all(), key=lambda r: r["id"])
    assert rows[3] == {"id": 3, "v": 6}


def test_groupby_aggregations(ray_start_regular):
    ds = rd.range(90, parallelism=6).map(lambda r: {"k": r["id"] % 3, "v": float(r["id"])})
    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 30, 1: 30, 2: 30}
    means = {r["k"]: r["mean(v)"] for r in ds.groupby("k").mean("v").take_all()}
    assert means[0] == pytest.approx(np.mean(np.arange(0, 90, 3)))
    # global aggs
    assert ds.min("v") == 0 and ds.max("v") == 89
    assert ds.std("v") == pytest.approx(np.std(np.arange(90), ddof=1))


def test_map_groups(ray_start_regular):
    ds = rd.range(30).map(lambda r: {"k": r["id"] % 3, "v": r["id"]})
    out = ds.groupby("k").map_groups(lambda g: {"k": g["k"][:1], "total": [g["v"].sum()]})
    rows = sorted(out.take_all(), key=lambda r: r["k"])
    assert rows[0]["total"] == sum(range(0, 30, 3))


def test_actor_compute_map_batches(ray_start_regular):
    class AddN:
        def __init__(self, n):
            self.n = n

        def __call__(self, batch):
            return {"id": batch["id"] + self.n}

    ds = rd.range(40, parallelism=4).map_batches(
        AddN, fn_constructor_args=(100,), concurrency=2
    )
    assert ds.sum("id") == sum(range(40)) + 100 * 40


def test_tensor_columns(ray_start_regular):
    arr = np.arange(60, dtype=np.float32).reshape(10, 2, 3)
    ds = rd.from_numpy(arr, column="x")
    batch = ds.take_batch(10, batch_format="numpy")
    assert batch["x"].shape == (10, 2, 3)
    np.testing.assert_array_equal(batch["x"], arr)
    out = ds.map_batches(lambda b: {"x": b["x"] * 2}).take_batch(10)
    np.testing.assert_array_equal(out["x"], arr * 2)


def test_from_pandas_arrow_items(ray_start_regular):
    import pandas as pd
    import pyarrow as pa

    df = pd.DataFrame({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    assert rd.from_pandas(df).count() == 3
    assert rd.from_arrow(pa.Table.from_pandas(df)).take(1)[0]["a"] == 1
    assert rd.from_items([{"a": 1}, {"a": 2}]).count() == 2
    assert rd.from_items([5, 6, 7]).take_all() == [{"item": 5}, {"item": 6}, {"item": 7}]


def test_file_roundtrips(ray_start_regular, tmp_path):
    ds = rd.range(50, parallelism=3).map(lambda r: {"id": r["id"], "txt": f"row{r['id']}"})
    pq_dir = str(tmp_path / "pq")
    ds.write_parquet(pq_dir)
    back = rd.read_parquet(pq_dir)
    assert back.count() == 50
    assert sorted(r["id"] for r in back.take_all()) == list(range(50))

    csv_dir = str(tmp_path / "csv")
    ds.write_csv(csv_dir)
    assert rd.read_csv(csv_dir).count() == 50

    js_dir = str(tmp_path / "js")
    ds.write_json(js_dir)
    assert rd.read_json(js_dir).count() == 50


def test_read_text_binary(ray_start_regular, tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("alpha\nbeta\n\ngamma\n")
    assert rd.read_text(str(p)).take_all() == [
        {"text": "alpha"},
        {"text": "beta"},
        {"text": "gamma"},
    ]
    bp = tmp_path / "f.bin"
    bp.write_bytes(b"\x00\x01\x02")
    rows = rd.read_binary_files(str(bp), include_paths=True).take_all()
    assert rows[0]["bytes"] == b"\x00\x01\x02"


def test_iter_batches_shapes(ray_start_regular):
    ds = rd.range(100, parallelism=4)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32)]
    assert sizes == [32, 32, 32, 4]
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32, drop_last=True)]
    assert sizes == [32, 32, 32]
    # local shuffle changes order but not content
    ids = [
        int(x)
        for b in ds.iter_batches(batch_size=10, local_shuffle_buffer_size=50, local_shuffle_seed=0)
        for x in b["id"]
    ]
    assert sorted(ids) == list(range(100)) and ids != list(range(100))


def test_iter_jax_batches(ray_start_regular):
    import jax.numpy as jnp

    ds = rd.range(32, parallelism=2)
    batches = list(ds.iter_jax_batches(batch_size=16, dtypes={"id": np.float32}))
    assert len(batches) == 2
    assert isinstance(batches[0]["id"], jnp.ndarray)
    assert batches[0]["id"].dtype == jnp.float32


def test_split_and_train_test_split(ray_start_regular):
    ds = rd.range(100, parallelism=10)
    splits = ds.split(4)
    assert sum(s.count() for s in splits) == 100
    eq = ds.split(4, equal=True)
    assert all(s.count() == 25 for s in eq)
    train, test = ds.train_test_split(0.2)
    assert train.count() == 80 and test.count() == 20


def test_streaming_split_multi_epoch(ray_start_regular):
    ds = rd.range(80, parallelism=8)
    its = ds.streaming_split(2, equal=False)

    # Epoch 1: both consumers drain concurrently via threads.
    import threading

    results = [[], []]

    def consume(i):
        for b in its[i].iter_batches(batch_size=10, prefetch_batches=0):
            results[i].extend(int(x) for x in b["id"])

    for epoch in range(2):
        results = [[], []]
        ts = [threading.Thread(target=consume, args=(i,)) for i in range(2)]
        [t.start() for t in ts]
        [t.join(timeout=60) for t in ts]
        assert sorted(results[0] + results[1]) == list(range(80))


def test_materialize_reuse(ray_start_regular):
    calls = []

    def trace(b):
        calls.append(1)
        return b

    ds = rd.range(20, parallelism=2).map_batches(trace).materialize()
    n_after_materialize = len(calls)
    assert ds.count() == 20 and ds.count() == 20
    assert len(calls) == n_after_materialize  # no re-execution


def test_unique_and_random_sample(ray_start_regular):
    ds = rd.range(100).map(lambda r: {"k": r["id"] % 5})
    assert ds.unique("k") == [0, 1, 2, 3, 4]
    frac = rd.range(1000, parallelism=4).random_sample(0.1, seed=0).count()
    assert 40 < frac < 250


def test_schema_and_stats(ray_start_regular):
    ds = rd.range(10)
    assert ds.columns() == ["id"]
    assert ds.size_bytes() > 0
    assert "rows=10" in ds.stats()


def test_sort_empty_after_filter(ray_start_regular):
    # Regression: sort over all-empty blocks must not crash.
    ds = rd.range(10, parallelism=2).filter(lambda r: r["id"] > 100).sort("id")
    assert ds.take_all() == []


def test_groupby_string_keys(ray_start_regular):
    # Regression: partitioning must use a process-stable hash for str keys.
    ds = rd.range(40, parallelism=4).map(lambda r: {"k": f"key{r['id'] % 4}", "v": 1})
    rows = ds.groupby("k").count().take_all()
    assert {r["k"]: r["count()"] for r in rows} == {f"key{i}": 10 for i in range(4)}


def test_early_break_iter_batches(ray_start_regular):
    # Regression: abandoning an iterator must not wedge threads/executors.
    ds = rd.range(1000, parallelism=8)
    for i, b in enumerate(ds.iter_batches(batch_size=10, prefetch_batches=2)):
        if i == 2:
            break
    assert ds.count() == 1000  # fresh execution still works


def test_tfrecords_roundtrip_signed(ray_start_regular, tmp_path):
    # Hand-written TFRecord file with bytes/float/negative-int features.
    import struct

    def _varint(x):
        out = b""
        while True:
            b7 = x & 0x7F
            x >>= 7
            if x:
                out += bytes([b7 | 0x80])
            else:
                out += bytes([b7])
                return out

    def _field(tag, wire, payload):
        return _varint((tag << 3) | wire) + payload

    def _ld(tag, data):
        return _field(tag, 2, _varint(len(data)) + data)

    def feature_int(vals):
        body = b"".join(_field(1, 0, _varint(v & ((1 << 64) - 1))) for v in vals)
        return _ld(3, body)

    def feature_bytes(v):
        return _ld(1, _ld(1, v))

    def kv(key, feat):
        return _ld(1, _ld(1, key.encode()) + _ld(2, feat))

    example = _ld(1, kv("label", feature_int([-1])) + kv("name", feature_bytes(b"abc")))
    rec = example
    path = tmp_path / "data.tfrecord"
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(rec)) + b"\x00" * 4 + rec + b"\x00" * 4)

    rows = rd.read_tfrecords(str(path)).take_all()
    assert rows[0]["label"] == -1
    assert rows[0]["name"] == b"abc"


def test_zip_tensor_shapes_and_collisions(ray_start_regular):
    # Regression: zip must keep per-column tensor shapes and never clobber.
    a = rd.from_numpy(np.arange(24, dtype=np.float32).reshape(6, 2, 2), column="data")
    b = rd.from_numpy(np.arange(18, dtype=np.float32).reshape(6, 3), column="data")
    batch = a.zip(b).take_batch(6)
    assert batch["data"].shape == (6, 2, 2)
    assert batch["data_1"].shape == (6, 3)


def test_streaming_split_equal_splits_remainder_rows(ray_start_regular):
    """equal=True with a bundle count not divisible by n: the trailing
    bundles' ROWS are re-sliced across consumers instead of dropped
    (reference: SplitCoordinator equalizes at row granularity)."""
    import threading

    # 5 bundles of 10 rows, 2 consumers: 2 full rounds (4 bundles) + 1
    # leftover bundle whose 10 rows must split 5/5
    ds = rd.range(50, parallelism=5)
    its = ds.streaming_split(2, equal=True)
    results = [[], []]

    def consume(i):
        for b in its[i].iter_batches(batch_size=100, prefetch_batches=0):
            results[i].extend(int(x) for x in b["id"])

    ts = [threading.Thread(target=consume, args=(i,)) for i in range(2)]
    [t.start() for t in ts]
    [t.join(timeout=60) for t in ts]
    assert len(results[0]) == len(results[1]) == 25
    assert sorted(results[0] + results[1]) == list(range(50))


def test_read_sql_sqlite(ray_start_regular, tmp_path):
    """SQL datasource over DB-API (reference: ray.data.read_sql)."""
    import sqlite3

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE users (id INTEGER, score REAL)")
    conn.executemany(
        "INSERT INTO users VALUES (?, ?)", [(i, i * 0.5) for i in range(50)]
    )
    conn.commit()
    conn.close()

    ds = rd.read_sql("SELECT id, score FROM users", lambda: sqlite3.connect(db))
    assert ds.count() == 50
    rows = ds.take(5)
    assert rows[0]["id"] == 0 and rows[4]["score"] == 2.0

    # windowed parallel read covers all rows exactly once
    ds4 = rd.read_sql(
        "SELECT id, score FROM users", lambda: sqlite3.connect(db),
        parallelism=4, order_by="id",
    )
    assert sorted(r["id"] for r in ds4.take_all()) == list(range(50))
