"""Worker registration timeout: a spawned worker that wedges before
registering is killed and respawned instead of hanging its waiters forever.

Reference: ``worker_register_timeout_seconds`` (ray_config_def.h) and the
startup-token accounting in raylet/worker_pool.h — the reference kills
non-registering workers after the deadline; we additionally retry the spawn
(bounded by ``worker_spawn_retries``) without charging actor-restart budget,
because a wedge at interpreter start is an environment hiccup, not an
application failure (observed in the wild as a worker stuck at 0 CPU with
only the interpreter's first 43 memory maps)."""

import os

import ray_tpu
from ray_tpu._private.config import GLOBAL_CONFIG


def _fast_timeout_config():
    return {"worker_register_timeout_s": 2.0, "health_check_interval_s": 0.2}


def test_wedged_pool_worker_killed_and_respawned(tmp_path, monkeypatch):
    sentinel = str(tmp_path / "wedge")
    monkeypatch.setenv("RAY_TPU_TEST_WEDGE_ONCE", sentinel)
    ray_tpu.init(num_cpus=1, _system_config=_fast_timeout_config())
    try:

        @ray_tpu.remote
        def f(x):
            return x + 1

        # the first spawn claims the sentinel and wedges pre-registration;
        # the health loop must kill it at the deadline and the respawn
        # completes the task
        assert ray_tpu.get(f.remote(41), timeout=60) == 42
        assert os.path.exists(sentinel), "fault injection never armed"
    finally:
        ray_tpu.shutdown()


def test_wedged_actor_worker_respawned_without_restart_budget(tmp_path, monkeypatch):
    sentinel = str(tmp_path / "wedge_actor")
    monkeypatch.setenv("RAY_TPU_TEST_WEDGE_ONCE", sentinel)
    ray_tpu.init(num_cpus=1, _system_config=_fast_timeout_config())
    try:

        @ray_tpu.remote
        class A:
            def ping(self):
                return "pong"

        # max_restarts defaults to 0: if the timeout path charged the actor
        # FSM, this creation would fail outright instead of respawning
        a = A.remote()
        assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
        assert os.path.exists(sentinel)
    finally:
        ray_tpu.shutdown()


def test_register_timeout_flag_lives_in_config():
    assert GLOBAL_CONFIG.worker_register_timeout_s > 0
    assert GLOBAL_CONFIG.worker_spawn_retries >= 1
