"""OTLP-JSON export: round-trips re-parsed against the OTLP field names.

The export is only useful if real OpenTelemetry tooling can read it, so
every assertion here goes through a full ``json.dumps``/``loads`` round
trip and checks the exact OTLP/JSON field names (``resourceSpans`` /
``scopeSpans`` / ``startTimeUnixNano`` / ``bucketCounts`` / ...), plus the
``obs export`` CLI end-to-end (live cluster and offline crash-flush).
"""

import json
import os

import pytest

import ray_tpu
from ray_tpu.util import metrics as um
from ray_tpu.util import otlp


def _roundtrip(doc):
    return json.loads(json.dumps(doc))


class TestOtlpMapping:
    def test_span_fields_and_trace_id_widening(self):
        rid = "abcd1234abcd1234"
        doc = _roundtrip(otlp.export(spans=[{
            "name": "llm_engine_step", "ph": "X", "ts": 2_000_000.0,
            "dur": 500_000.0, "pid": "proc-42", "tid": "thread-1",
            "args": {"request_id": rid, "step": 3},
        }]))
        rs = doc["resourceSpans"]
        assert len(rs) == 1
        res_attrs = {
            a["key"]: a["value"] for a in rs[0]["resource"]["attributes"]
        }
        assert res_attrs["service.name"] == {"stringValue": "ray_tpu"}
        span = rs[0]["scopeSpans"][0]["spans"][0]
        assert span["name"] == "llm_engine_step"
        assert len(span["traceId"]) == 32 and span["traceId"].endswith(rid)
        assert len(span["spanId"]) == 16
        assert span["startTimeUnixNano"] == str(2_000_000 * 1000)
        assert span["endTimeUnixNano"] == str(2_500_000 * 1000)
        attrs = {a["key"]: a["value"] for a in span["attributes"]}
        assert attrs["step"] == {"intValue": "3"}

    def test_event_log_records(self):
        doc = _roundtrip(otlp.export(events=[
            {"ts": 1.5, "type": "llm.first_token", "pid": 7, "node": "ab12",
             "request_id": "abcd1234abcd1234", "ttft_s": 0.12},
            {"ts": 2.0, "type": "crash.sigterm", "pid": 7, "node": "ab12"},
            {"ts": 2.5, "type": "alert.fire", "pid": 1, "rule": "ttft-p99"},
        ]))
        logs = doc["resourceLogs"]
        all_recs = [r for rl in logs for r in rl["scopeLogs"][0]["logRecords"]]
        assert len(all_recs) == 3
        first = next(
            r for r in all_recs if r["body"]["stringValue"] == "llm.first_token"
        )
        assert first["timeUnixNano"] == "1500000000"
        assert first["severityText"] == "INFO"
        assert len(first["traceId"]) == 32
        crash = next(
            r for r in all_recs if r["body"]["stringValue"] == "crash.sigterm"
        )
        assert crash["severityText"] == "ERROR"
        fire = next(
            r for r in all_recs if r["body"]["stringValue"] == "alert.fire"
        )
        assert fire["severityText"] == "WARN"
        # node rides the resource, not each record
        nodes = {
            a["value"].get("stringValue")
            for rl in logs for a in rl["resource"]["attributes"]
            if a["key"] == "node.id"
        }
        assert "ab12" in nodes

    def test_metric_kinds_map_to_sum_gauge_histogram(self):
        series = {
            "llm_generated_tokens": {"kind": "counter", "boundaries": None,
                                     "series": {"": [(1.0, 5.0), (2.0, 9.0)]}},
            "llm_kv_block_utilization": {"kind": "gauge", "boundaries": None,
                                         "series": {"": [(1.0, 0.5)]}},
            "llm_time_to_first_token_s": {
                "kind": "histogram", "boundaries": [0.1, 1.0],
                "series": {"": [(1.0, [1, 2, 3, 4.5, 6])]},
            },
        }
        doc = _roundtrip(otlp.export(series=series))
        metrics = doc["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
        assert len(metrics) >= 3
        by_name = {m["name"]: m for m in metrics}
        ctr = by_name["ray_tpu_llm_generated_tokens"]["sum"]
        assert ctr["isMonotonic"] is True
        assert ctr["aggregationTemporality"] == 2
        assert ctr["dataPoints"][0]["asDouble"] == 5.0
        assert ctr["dataPoints"][0]["timeUnixNano"] == "1000000000"
        gauge = by_name["ray_tpu_llm_kv_block_utilization"]["gauge"]
        assert gauge["dataPoints"][0]["asDouble"] == 0.5
        hist = by_name["ray_tpu_llm_time_to_first_token_s"]["histogram"]
        dp = hist["dataPoints"][0]
        assert dp["bucketCounts"] == ["1", "2", "3"]
        assert dp["explicitBounds"] == [0.1, 1.0]
        assert dp["count"] == "6"
        assert dp["sum"] == 4.5

    def test_tagged_series_become_datapoint_attributes(self):
        tag = json.dumps({"status": "5xx"})
        doc = _roundtrip(otlp.export(series={
            "serve_requests": {"kind": "counter", "boundaries": None,
                               "series": {tag: [(1.0, 3.0)]}},
        }))
        dp = doc["resourceMetrics"][0]["scopeMetrics"][0]["metrics"][0][
            "sum"]["dataPoints"][0]
        attrs = {a["key"]: a["value"] for a in dp["attributes"]}
        assert attrs["status"] == {"stringValue": "5xx"}

    def test_http_sink_is_best_effort(self, monkeypatch):
        # an unreachable collector reports, never raises
        monkeypatch.setenv("RAY_TPU_OTLP_ENDPOINT", "http://127.0.0.1:9")
        doc = otlp.export(events=[{"ts": 1.0, "type": "x", "pid": 1}])
        out = otlp.post(doc, timeout=0.5)
        assert "/v1/logs" in out
        assert str(out["/v1/logs"]).startswith("error:")


class TestObsExportCli:
    def test_offline_export_from_crash_files(self, tmp_path):
        from ray_tpu.obs import main as obs_main

        d = tmp_path / "events"
        d.mkdir()
        with open(d / "events-1.jsonl", "w") as f:
            f.write(json.dumps({"_flight_recorder": 1, "pid": 1,
                                "node": "ab", "reason": "sigterm"}) + "\n")
            f.write(json.dumps({"seq": 0, "ts": 1.0, "type": "crash.sigterm",
                                "pid": 1}) + "\n")
        out = tmp_path / "otlp.json"
        rc = obs_main([
            "export", "--otlp", "--events-dir", str(d), "-o", str(out),
        ])
        assert rc == 0
        doc = json.loads(out.read_text())
        recs = doc["resourceLogs"][0]["scopeLogs"][0]["logRecords"]
        assert recs[0]["body"]["stringValue"] == "crash.sigterm"
        assert doc["resourceSpans"] == [] and doc["resourceMetrics"] == []

    # tier-1 budget (ISSUE 13): 12.3s measured on the dev box (boots a
    # full cluster just for the export); the offline-export tests above
    # pin the same field names, and CI's postmortem steps run the live
    # CLI on every failure artifact anyway
    @pytest.mark.slow
    def test_live_export_has_spans_events_and_series(self, tmp_path):
        """The acceptance shape: a live cluster with engine-style metrics,
        spans, and events exports ≥3 metric series plus spans and events,
        all re-parsed under OTLP field names."""
        um._reset_series_for_tests()
        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            from ray_tpu.util import tracing

            c = um.Counter("llm_generated_tokens", "tokens")
            g = um.Gauge("llm_kv_block_utilization", "kv")
            h = um.Histogram("llm_time_to_first_token_s", "ttft")
            with tracing.trace_context("feedbeef12345678"):
                with tracing.span("llm_engine_step", step=1):
                    c.inc(10)
                    g.set(0.4)
                    h.observe(0.05)
            from ray_tpu._private import events as fr

            fr.record("llm.first_token", request_id="feedbeef12345678",
                      ttft_s=0.05)
            um.sample_series_now()
            um.flush()
            um.sample_series_now()
            um.flush()
            out = tmp_path / "otlp.json"
            doc, counts = otlp.export_cluster(path=str(out))
            assert counts["spans"] >= 1
            assert counts["events"] >= 1
            assert counts["metrics"] >= 3
            parsed = json.loads(out.read_text())
            span_names = {
                s["name"]
                for r in parsed["resourceSpans"]
                for ss in r["scopeSpans"] for s in ss["spans"]
            }
            assert "llm_engine_step" in span_names
            metric_names = {
                m["name"]
                for r in parsed["resourceMetrics"]
                for sm in r["scopeMetrics"] for m in sm["metrics"]
            }
            assert {"ray_tpu_llm_generated_tokens",
                    "ray_tpu_llm_kv_block_utilization",
                    "ray_tpu_llm_time_to_first_token_s"} <= metric_names
            # the span and the event share the request's 32-hex traceId
            tid = next(
                s["traceId"]
                for r in parsed["resourceSpans"]
                for ss in r["scopeSpans"] for s in ss["spans"]
                if s["name"] == "llm_engine_step"
            )
            log_tids = {
                rec.get("traceId")
                for r in parsed["resourceLogs"]
                for sl in r["scopeLogs"] for rec in sl["logRecords"]
            }
            assert tid in log_tids
        finally:
            ray_tpu.shutdown()
            um._reset_series_for_tests()
