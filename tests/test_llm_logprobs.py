"""Logprob capture (the rlhf behavior-policy contract, models.sampling
module doc): sampled + greedy decode return logprobs that exactly match
recomputing log_softmax at the sampled ids, identical with spec decode
on vs off, and stable across a mid-stream failover resume (the PR-6
absolute-index PRNG contract extends to logprobs)."""

import math

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.llm.engine import EngineConfig, LLMEngine  # noqa: E402
from ray_tpu.llm.scheduler import SamplingParams  # noqa: E402
from ray_tpu.models.gpt import GPTConfig, gpt_forward, gpt_init  # noqa: E402
from ray_tpu.models.sampling import (  # noqa: E402
    sample_tokens,
    sample_tokens_logprobs,
    token_logprobs,
)

TINY = GPTConfig(
    vocab_size=32, seq_len=96, d_model=32, n_layers=2, n_heads=2,
    remat=False, fused_loss=False, dtype="float32",
)


@pytest.fixture(scope="module")
def tiny_params():
    return gpt_init(jax.random.PRNGKey(0), TINY)


def _engine(params, **over):
    cfg = dict(
        max_slots=2, num_blocks=64, block_size=4, max_blocks_per_seq=16,
        prefill_chunk=8,
    )
    cfg.update(over)
    return LLMEngine(TINY, params, EngineConfig(**cfg))


def _run(engine, prompt, params, resume=()):
    req = engine.submit(prompt, params, resume_tokens=resume)
    while not req.finished:
        if not engine.step():
            break
    return req


# ---------------------------------------------------------------------------
# unit: the sampling-layer contract
# ---------------------------------------------------------------------------


class TestSamplingLogprobs:
    def test_greedy_matches_raw_log_softmax(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (4, 16)) * 3.0
        tok, lp = sample_tokens_logprobs(logits, jax.random.PRNGKey(2))
        ref = np.log(
            np.exp(np.asarray(logits, np.float64))
            / np.exp(np.asarray(logits, np.float64)).sum(-1, keepdims=True)
        )
        am = np.argmax(np.asarray(logits), axis=-1)
        assert np.array_equal(np.asarray(tok), am)
        np.testing.assert_allclose(
            np.asarray(lp), ref[np.arange(4), am], atol=1e-5
        )

    def test_sampled_matches_filtered_log_softmax(self):
        """Independent numpy recompute of the filtered distribution:
        temperature-scale, keep top-k, renormalize — the captured logprob
        is log_softmax of exactly that."""
        logits = jax.random.normal(jax.random.PRNGKey(3), (8, 16)) * 2.0
        temp, k = 1.3, 5
        tok, lp = sample_tokens_logprobs(
            logits, jax.random.PRNGKey(4), temperature=temp, top_k=k
        )
        scaled = np.asarray(logits, np.float64) / temp
        for i in range(8):
            row = scaled[i]
            keep = np.argsort(-row)[:k]
            assert int(tok[i]) in keep  # never samples a masked id
            z = np.exp(row[keep] - row[keep].max())
            p = z / z.sum()
            ref = math.log(p[list(keep).index(int(tok[i]))])
            assert abs(float(lp[i]) - ref) < 1e-5

    def test_token_logprobs_scores_identically(self):
        """The learner-side scorer returns the same number the sampler
        captured — for every row, sampled and greedy alike."""
        logits = jax.random.normal(jax.random.PRNGKey(5), (6, 16))
        temps = jnp.asarray([0.0, 1.0, 0.7, 0.0, 2.0, 1.0])
        tok, lp = sample_tokens_logprobs(
            logits, jax.random.PRNGKey(6), temperature=temps, top_k=4
        )
        scored = token_logprobs(logits, tok, temperature=temps, top_k=4)
        np.testing.assert_allclose(np.asarray(lp), np.asarray(scored), atol=1e-6)

    def test_sample_tokens_unchanged_by_capture(self):
        """The logprob path must not perturb token choice (sample_tokens
        is the token-identity contract everything else pins against)."""
        logits = jax.random.normal(jax.random.PRNGKey(7), (5, 16))
        key = jax.random.PRNGKey(8)
        t1 = sample_tokens(logits, key, temperature=0.9, top_p=0.8)
        t2, _ = sample_tokens_logprobs(logits, key, temperature=0.9, top_p=0.8)
        assert np.array_equal(np.asarray(t1), np.asarray(t2))

    def test_masked_token_scores_filtered_out(self):
        """Scoring an id the filter excluded reports ~p=0 (the honest
        behavior-density for a token the policy could not have sampled)."""
        logits = jnp.asarray([[5.0, 4.0, -1.0, -2.0]])
        lp = token_logprobs(
            logits, jnp.asarray([3]), temperature=1.0, top_k=2
        )
        assert float(lp[0]) < -1e20


# ---------------------------------------------------------------------------
# engine: capture matches a dense-forward recompute
# ---------------------------------------------------------------------------


def _dense_logprobs(params, prompt, out, temperature=0.0, top_k=0, top_p=1.0):
    full = list(prompt) + list(out)
    logits = gpt_forward(TINY, params, jnp.asarray([full], jnp.int32))[0]
    pos = jnp.asarray([len(prompt) - 1 + i for i in range(len(out))])
    return np.asarray(
        token_logprobs(
            logits[pos], jnp.asarray(out), temperature, top_k, top_p
        )
    )


class TestEngineCapture:
    @pytest.mark.parametrize(
        "sp",
        [
            SamplingParams(max_tokens=10),
            SamplingParams(max_tokens=10, temperature=1.0, seed=5),
            SamplingParams(max_tokens=10, temperature=0.8, top_k=6, seed=9),
        ],
        ids=["greedy", "sampled", "topk"],
    )
    def test_matches_dense_recompute(self, tiny_params, sp):
        eng = _engine(tiny_params)
        req = _run(eng, [1, 2, 3], sp)
        assert len(req.out_logprobs) == len(req.out)
        ref = _dense_logprobs(
            tiny_params, [1, 2, 3], req.out, sp.temperature, sp.top_k, sp.top_p
        )
        np.testing.assert_allclose(req.out_logprobs, ref, atol=2e-4)

    # tier-1 budget (ISSUE 20): 10.9s measured — rides slow;
    # tests/test_llm_spec.py keeps spec-decode token identity in tier-1 and
    # the logprob-capture goldens above keep the capture contract gated
    @pytest.mark.slow
    def test_spec_decode_on_vs_off_identical(self, tiny_params):
        """Spec decode must capture the SAME logprobs the plain path
        captures — the verify path computes per-index distributions, so
        the capture rides the same math. Repetitive prompt exercises
        real acceptance."""
        prompt = [1, 2, 3, 1, 2, 3, 1, 2, 3]
        for sp in (
            SamplingParams(max_tokens=16),
            SamplingParams(max_tokens=16, temperature=1.0, seed=3),
        ):
            plain = _run(_engine(tiny_params), prompt, sp)
            spec = _run(
                _engine(tiny_params, spec_k=3), prompt, sp
            )
            assert spec.out == plain.out  # existing token-identity contract
            np.testing.assert_allclose(
                spec.out_logprobs, plain.out_logprobs, atol=1e-4
            )

    def test_failover_resume_logprob_stability(self, tiny_params):
        """Absolute-index contract: resuming from a delivered prefix
        reproduces the SAME logprobs at every new index; the resumed
        (dead-replica) prefix reports NaN — unknown, never fabricated."""
        sp = SamplingParams(max_tokens=12, temperature=1.0, seed=11)
        orig = _run(_engine(tiny_params), [4, 5, 6], sp)
        assert len(orig.out) == 12
        cut = 5
        resumed = _run(
            _engine(tiny_params), [4, 5, 6], sp, resume=tuple(orig.out[:cut])
        )
        assert resumed.out == orig.out  # token identity (PR 6 contract)
        assert all(math.isnan(x) for x in resumed.out_logprobs[:cut])
        np.testing.assert_allclose(
            resumed.out_logprobs[cut:], orig.out_logprobs[cut:], atol=1e-4
        )
