"""Task-path fast-lane regressions: getter-pumped worker IO, coalesced
dispatch batches, ref-taking submits (reference: ``ray_perf.py`` themes +
the ordering/liveness properties the optimizations must preserve)."""

import threading

import pytest

import ray_tpu


def test_concurrent_getters_no_lost_wakeups(ray_start_regular):
    """Many threads in blocking get() while tasks storm: the pump mutex
    hands off between getters and the IO thread without stranding anyone
    (regression for the pump-select race that stalled sync gets)."""

    @ray_tpu.remote
    def sq(x):
        return x * x

    errors = []

    def getter(base):
        try:
            for i in range(40):
                assert ray_tpu.get(sq.remote(base + i), timeout=60) == (base + i) ** 2
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=getter, args=(k * 100,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "getter thread wedged"
    assert not errors, errors


def test_dispatch_batch_preserves_fifo(ray_start_regular):
    """A burst of pipelined tasks to one worker may coalesce into a
    run_task_batch; execution order must remain submission order (actor
    FIFO semantics ride the same conn ordering)."""

    @ray_tpu.remote
    class Log:
        def __init__(self):
            self.seen = []

        def add(self, i):
            self.seen.append(i)
            return i

        def all(self):
            return self.seen

    log = Log.remote()
    refs = [log.add.remote(i) for i in range(200)]
    ray_tpu.get(refs, timeout=120)
    assert ray_tpu.get(log.all.remote(), timeout=60) == list(range(200))


def test_submit_takes_return_refs(ray_start_regular):
    """head.submit_task itself must take the submitter's ref on return ids
    (no separate add_ref round trip): the ref survives until the driver
    drops it, then the object is evicted."""
    from ray_tpu._private.runtime import get_ctx

    @ray_tpu.remote
    def val():
        return 123

    ref = val.remote()
    assert ray_tpu.get(ref, timeout=60) == 123
    head = get_ctx().head
    with head.lock:
        ent = head.objects.get(ref.binary())
        assert ent is not None and ent.refcount >= 1
    oid = ref.binary()
    del ref
    # the gc drain queue frees asynchronously; poll briefly
    import time

    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with head.lock:
            if head.objects.get(oid) is None:
                break
        time.sleep(0.05)
    with head.lock:
        assert head.objects.get(oid) is None, "return ref leaked after del"


def test_nested_submit_single_round_trip(ray_start_regular):
    """Workers submitting subtasks get results back correctly through the
    folded submit (and the pump handles nested gets on pool threads)."""

    @ray_tpu.remote
    def leaf(x):
        return x + 1

    @ray_tpu.remote
    def parent(x):
        return ray_tpu.get([leaf.remote(x + i) for i in range(8)])

    out = ray_tpu.get(parent.remote(100), timeout=120)
    assert out == [101 + i for i in range(8)]


def test_task_ids_unique_across_storm(ray_start_regular):
    """The nonce+counter task-id source must never collide within or
    across processes (workers submit with their own contexts)."""

    @ray_tpu.remote
    def ids(n):
        from ray_tpu._private.runtime import get_ctx

        return [get_ctx().new_task_returns(1)[0] for _ in range(n)]

    batches = ray_tpu.get([ids.remote(200) for _ in range(4)], timeout=120)
    from ray_tpu._private.runtime import get_ctx

    local = [get_ctx().new_task_returns(1)[0] for _ in range(200)]
    flat = [tid for b in batches for tid in b] + local
    assert len(set(flat)) == len(flat), "task id collision"
