"""Unit tests for the raylint dataflow phase (ray_tpu._lint.dataflow):
CFG construction (branch/loop/try/finally shapes, exception edges, branch
labels), the forward fixpoint engine in both may and must modes, and the
jit donation/static summaries the RL013/RL014 rules consume."""

import ast
import textwrap

from ray_tpu._lint import dataflow
from ray_tpu._lint.core import FileContext
from ray_tpu._lint.index import build_index


def _fn(src, name=None):
    tree = ast.parse(textwrap.dedent(src))
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and (name is None or node.name == name):
            return node
    raise AssertionError("no function in snippet")


def _cfg(src, name=None):
    return dataflow.build_cfg(_fn(src, name))


def _reachable(cfg):
    seen = set()
    work = [cfg.entry]
    while work:
        n = work.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        work.extend(n.succ)
        work.extend(n.esucc)
    return seen


def _stmt_nodes(cfg, kind=None):
    return [
        n
        for n in cfg.nodes
        if n.stmt is not None and id(n) in _reachable(cfg) and (
            kind is None or isinstance(n.stmt, kind)
        )
    ]


# ------------------------------------------------------------------ CFG


def test_linear_flow_reaches_exit():
    cfg = _cfg("""
        def f(x):
            y = x + 1
            return y
    """)
    assert id(cfg.exit) in _reachable(cfg)
    ret = _stmt_nodes(cfg, ast.Return)[0]
    assert cfg.exit in ret.succ


def test_if_branches_are_labeled():
    cfg = _cfg("""
        def f(x):
            if x:
                a = 1
            else:
                a = 2
            return a
    """)
    head = _stmt_nodes(cfg, ast.If)[0]
    labels = sorted(head.succ_label.values())
    assert labels == ["false", "true"]


def test_if_without_else_labels_fallthrough():
    cfg = _cfg("""
        def f(x):
            if x:
                a = 1
            return x
    """)
    head = _stmt_nodes(cfg, ast.If)[0]
    assert list(head.succ_label.values()) == ["true"]
    assert head.fallthrough_label == "false"


def test_loop_has_back_edge_and_break_exit():
    cfg = _cfg("""
        def f(xs):
            out = 0
            for x in xs:
                if x < 0:
                    break
                out += x
            return out
    """)
    head = _stmt_nodes(cfg, (ast.For,))[0]
    # the body's last statement loops back to the header
    aug = _stmt_nodes(cfg, ast.AugAssign)[0]
    assert head in aug.succ
    # break reaches the return without passing the header again
    brk = _stmt_nodes(cfg, ast.Break)[0]
    ret = _stmt_nodes(cfg, ast.Return)[0]
    seen, work = set(), list(brk.succ)
    while work:
        n = work.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        work.extend(n.succ)
    assert id(ret) in seen and id(head) not in seen


def test_call_statement_has_exception_edge_to_raise_exit():
    cfg = _cfg("""
        def f(x):
            g(x)
            return x
    """)
    call = [n for n in _stmt_nodes(cfg) if isinstance(n.stmt, ast.Expr)][0]
    assert cfg.raise_exit in call.esucc


def test_narrow_handler_keeps_escape_edge():
    cfg = _cfg("""
        def f(x):
            try:
                g(x)
            except OSError:
                pass
            return x
    """)
    call = [n for n in _stmt_nodes(cfg) if isinstance(n.stmt, ast.Expr)][0]
    # handler entry AND the escape (OSError is not catch-all)
    assert cfg.raise_exit in call.esucc
    assert len(call.esucc) == 2


def test_catch_all_handler_stops_escape():
    cfg = _cfg("""
        def f(x):
            try:
                g(x)
            except Exception:
                pass
            return x
    """)
    call = [n for n in _stmt_nodes(cfg) if isinstance(n.stmt, ast.Expr)][0]
    assert cfg.raise_exit not in call.esucc


def test_finally_on_exception_path():
    cfg = _cfg("""
        def f(x):
            try:
                g(x)
            finally:
                release(x)
            return x
    """)
    call = [
        n for n in _stmt_nodes(cfg)
        if isinstance(n.stmt, ast.Expr)
        and isinstance(n.stmt.value, ast.Call)
        and n.stmt.value.func.id == "g"
    ][0]
    # exception routes through the finally copy, not straight out
    assert cfg.raise_exit not in call.esucc
    assert len(call.esucc) == 1
    fin = call.esucc[0]
    assert isinstance(fin.stmt, ast.Expr)  # the release(x) copy
    assert cfg.raise_exit in [s for s in fin.succ]


def test_return_routes_through_finally():
    cfg = _cfg("""
        def f(x):
            try:
                return g(x)
            finally:
                release(x)
    """)
    ret = _stmt_nodes(cfg, ast.Return)[0]
    (fin,) = ret.succ
    assert isinstance(fin.stmt, ast.Expr)  # the finally's release copy
    assert cfg.exit in fin.succ


def test_raise_statement_targets_handlers():
    cfg = _cfg("""
        def f(x):
            try:
                raise ValueError(x)
            except ValueError:
                return 1
    """)
    rz = _stmt_nodes(cfg, ast.Raise)[0]
    assert rz.succ == [] and len(rz.esucc) == 2  # handler + escape


# ------------------------------------------------------------- fixpoint


def _assign_analysis(cfg, join):
    """Toy definite/possible-assignment analysis over Name stores."""

    def transfer(node, state):
        stmt = node.stmt
        if stmt is None:
            return state, state
        new = set(state)
        for chain in dataflow.store_chains(stmt):
            if len(chain) == 1:
                new.add(chain[0])
        return frozenset(new), state

    return dataflow.fixpoint(cfg, transfer, join=join)


def test_fixpoint_may_vs_must_join():
    cfg = _cfg("""
        def f(x):
            if x:
                a = 1
            else:
                b = 2
            return x
    """)
    ret = _stmt_nodes(cfg, ast.Return)[0]
    may = _assign_analysis(cfg, "may")[ret]
    must = _assign_analysis(cfg, "must")[ret]
    assert may == frozenset({"a", "b"})   # assigned on SOME path
    assert must == frozenset()            # on EVERY path: neither


def test_fixpoint_must_keeps_common_facts():
    cfg = _cfg("""
        def f(x):
            if x:
                a = 1
                c = 3
            else:
                a = 2
            return x
    """)
    ret = _stmt_nodes(cfg, ast.Return)[0]
    must = _assign_analysis(cfg, "must")[ret]
    assert must == frozenset({"a"})


def test_fixpoint_loop_terminates_and_unions():
    cfg = _cfg("""
        def f(xs):
            for x in xs:
                y = x
            return xs
    """)
    ret = _stmt_nodes(cfg, ast.Return)[0]
    may = _assign_analysis(cfg, "may")[ret]
    assert may == frozenset({"x", "y"})


# ------------------------------------------------- summaries / resolution


def _index_for(tmp_path, sources):
    contexts = []
    for name, src in sources.items():
        f = tmp_path / name
        f.write_text(textwrap.dedent(src))
        contexts.append(
            FileContext(f, name, f.read_text(), ast.parse(f.read_text()))
        )
    return build_index(contexts)


def test_jit_registry_records_donate_argnums(tmp_path):
    index = _index_for(tmp_path, {"m.py": """
        import jax

        class R:
            def __init__(self):
                self._step = jax.jit(self._impl, donate_argnums=(1, 2))

            def _impl(self, p, k, v):
                return k, v
    """})
    sites = [s for s, _ in index.jit_sites]
    assert any(s.donate_argnums == (1, 2) for s in sites)


def test_summary_lifts_donation_one_level(tmp_path):
    index = _index_for(tmp_path, {"m.py": """
        import jax

        class R:
            def __init__(self):
                self._step = jax.jit(self._impl, donate_argnums=(1, 2))

            def _impl(self, p, k, v):
                return k, v

            def step(self, k_pool, v_pool):
                return self._step(self.p, k_pool, v_pool)
    """})
    cache = dataflow.get_cache(index)
    step = index.functions["m:R.step"]
    summ = cache.summary(step)
    # param-index space includes self: k_pool=1, v_pool=2
    assert summ is not None and summ.donate == (1, 2)


def test_resolve_shifts_bound_method_positions(tmp_path):
    index = _index_for(tmp_path, {
        "m.py": """
            import jax

            class R:
                def __init__(self):
                    self._step = jax.jit(self._impl, donate_argnums=(1,))

                def _impl(self, p, k):
                    return k

                def step(self, k_pool):
                    return self._step(self.p, k_pool)
        """,
        "e.py": """
            from m import R

            class E:
                def __init__(self):
                    self.runner = R()

                def go(self, buf):
                    out = self.runner.step(buf)
                    return out
        """,
    })
    cache = dataflow.get_cache(index)
    go = index.functions["e:E.go"]
    call = next(cs.node for cs in go.calls if cs.chain[-1] == "step")
    res = cache.resolve(go, call)
    assert res is not None and res.donate == (0,)


def test_factory_returned_jit_resolves(tmp_path):
    index = _index_for(tmp_path, {"m.py": """
        import jax

        def make_step(fn):
            return jax.jit(fn, donate_argnums=(0,))

        def train(state, batch):
            step = make_step(lambda s, b: s)
            state2 = step(state, batch)
            return state2
    """})
    cache = dataflow.get_cache(index)
    train = index.functions["m:train"]
    call = next(
        cs.node for cs in train.calls if cs.chain == ("step",)
    )
    res = cache.resolve(train, call)
    assert res is not None and res.donate == (0,)


def test_unresolvable_parameter_callable_is_skipped(tmp_path):
    # a jitted callable arriving as a PARAMETER is not resolvable — the
    # analyses must under-approximate, not guess
    index = _index_for(tmp_path, {"m.py": """
        def drive(step_fn, state, batch):
            state = step_fn(state, batch)
            return state
    """})
    cache = dataflow.get_cache(index)
    drive = index.functions["m:drive"]
    call = next(cs.node for cs in drive.calls)
    assert cache.resolve(drive, call) is None


def test_conditional_acquire_polarity():
    fn = _fn("""
        def f(self, blk):
            if not self.pool.cache_retain(blk):
                return 0
            return 1
    """)
    test = fn.body[0].test
    call = next(
        n for n in ast.walk(test) if isinstance(n, ast.Call)
    )
    assert dataflow._polarity_in(test, call) is False
    other = ast.parse("x or y").body[0].value
    assert dataflow._polarity_in(other, call) is None


def test_summary_cites_the_contributing_jit_site(tmp_path):
    # a later static-only jit call must not steal the site citation from
    # the donating call RL013's message points at
    index = _index_for(tmp_path, {"m.py": """
        import jax

        class R:
            def __init__(self):
                self._step = jax.jit(self._impl, donate_argnums=(1,))
                self._other = jax.jit(self._oimpl, static_argnums=(1,))

            def _impl(self, p, k):
                return k

            def _oimpl(self, x, n):
                return x

            def step(self, k_pool):
                out = self._step(self.p, k_pool)
                self._other(out, 3)
                return out
    """})
    cache = dataflow.get_cache(index)
    summ = cache.summary(index.functions["m:R.step"])
    assert summ is not None and summ.donate == (1,)
    assert "self._step" in summ.desc
