"""Evolution strategies (reference: ``rllib/algorithms/es`` + ``ars``
tuned-example themes, scaled to CI)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl.algorithms.es import ES, ESConfig


def _config(num_env_runners=0, seed=0):
    cfg = ESConfig()
    cfg.env = "CartPole-v1"
    cfg.seed = seed
    cfg.num_env_runners = num_env_runners
    cfg.num_rollouts = 8
    cfg.sigma = 0.3
    cfg.lr = 0.2
    cfg.top_frac = 0.5
    cfg.eval_max_steps = 1000
    cfg.hidden = [32]
    return cfg


def test_es_learns_cartpole_local():
    algo = ES(_config())
    best = 0.0
    try:
        for _ in range(25):
            result = algo.train()
            ret = result.get("episode_return_mean") or 0.0
            best = max(best, ret)
            if best >= 100.0:
                break
    finally:
        algo.stop()
    assert best >= 100.0, f"ES did not learn: best return {best}"


def test_es_update_is_deterministic_given_seed():
    a = ES(_config(seed=7))
    b = ES(_config(seed=7))
    try:
        a.train()
        b.train()
        assert np.allclose(a._theta, b._theta)
    finally:
        a.stop()
        b.stop()


def test_es_distributed_runners(ray_start_regular):
    algo = ES(_config(num_env_runners=2))
    try:
        result = algo.train()
        assert result["training_iteration"] == 1
        assert result["timesteps_total"] > 0
        assert result.get("episode_return_mean") is not None
    finally:
        algo.stop()


def test_es_registered_for_tune():
    from ray_tpu.tune.registry import resolve_trainable

    assert resolve_trainable("ES") is not None
    assert resolve_trainable("ARS") is not None
