"""MoE + expert parallelism tests.

The reference has no MoE/EP (SURVEY §2.4: "Expert parallel — not
implemented"); the TPU build makes it first-class: GShard-style dense
dispatch sharded over the ``ep`` mesh axis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models.gpt import GPTConfig, gpt_forward, gpt_init, gpt_loss
from ray_tpu.parallel.mesh import MeshConfig, make_mesh
from ray_tpu.parallel.train_step import build_train_step


def _cfg(**kw):
    base = dict(
        vocab_size=256, seq_len=64, d_model=64, n_layers=2, n_heads=2,
        dtype="float32", n_experts=4, experts_per_token=2,
    )
    base.update(kw)
    return GPTConfig(**base)


def test_moe_forward_shapes_and_aux():
    cfg = _cfg()
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    assert "moe_in" in params["blocks"] and "router" in params["blocks"]
    assert params["blocks"]["moe_in"]["kernel"].shape == (2, 4, 64, 256)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 256, jnp.int32)
    logits, aux = gpt_forward(cfg, params, tokens, return_aux=True)
    assert logits.shape == (2, 64, 256)
    # balanced-ish routing at init: aux near k (its value under uniform routing)
    assert 0.5 < float(aux) < 6.0


def test_moe_capacity_drops_dont_nan():
    cfg = _cfg(capacity_factor=0.5)  # force heavy dropping
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 256, jnp.int32)
    loss = gpt_loss(cfg, params, tokens)
    assert np.isfinite(float(loss))


# tier-1 budget (ISSUE 20): 7.9s measured — the loss-decrease training loop
# rides slow; forward shapes/aux, capacity drops, EP-sharding parity and the
# dense-config guard keep MoE correctness in tier-1
@pytest.mark.slow
def test_moe_trains_loss_decreases():
    cfg = _cfg()
    mesh = make_mesh(MeshConfig(dp=2, fsdp=1, ep=2, tp=2), devices=jax.devices()[:8])
    init_fn, step_fn = build_train_step(
        lambda p, t: gpt_loss(cfg, p, t, mesh), optax.adamw(1e-3), mesh
    )
    state = init_fn(gpt_init(jax.random.PRNGKey(0), cfg))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 65), 0, 256, jnp.int32)
    state, l0 = step_fn(state, tokens)
    for _ in range(5):
        state, loss = step_fn(state, tokens)
    assert float(loss) < float(l0), (float(l0), float(loss))


def test_moe_ep_sharding_matches_single_device():
    """ep=2-sharded forward == single-device forward (same params/tokens)."""
    cfg = _cfg()
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 256, jnp.int32)
    ref = gpt_forward(cfg, params, tokens)

    mesh = make_mesh(MeshConfig(dp=1, fsdp=1, ep=2, tp=2), devices=jax.devices()[:4])
    with mesh:
        out = jax.jit(lambda p, t: gpt_forward(cfg, p, t, mesh))(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_dense_config_unchanged():
    cfg = _cfg(n_experts=0)
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    assert "mlp_in" in params["blocks"] and "router" not in params["blocks"]
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 256, jnp.int32)
    assert np.isfinite(float(gpt_loss(cfg, params, tokens)))
