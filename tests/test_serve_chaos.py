"""Serve-plane chaos: streaming LLM traffic vs SIGKILL (RESILIENCE.md).

The acceptance scenarios for fault-tolerant serving:

* a streaming LLM request whose replica is SIGKILLed MID-GENERATION
  completes with a token sequence identical to an unkilled run — greedy
  and seeded sampling (resumable streams: the handle journals delivered
  tokens and re-submits ``resume_tokens`` to a fresh replica; per-token
  PRNG keys derive from (seed, absolute output index) so the failover
  boundary cannot change the sequence);
* a chaos soak — sustained concurrent streams while ``ServeReplicaKiller``
  SIGKILLs replicas on a timer — finishes EVERY stream token-identically
  (never hung, never truncated, never wrong);
* killing the serve CONTROLLER mid-stream (here: while a downscaled
  replica is draining) leaves the data plane serving — streams complete,
  and a fresh ``serve.run`` recovers the control plane;
* overload shedding: a doomed deadline gets ``429 Too Many Requests``
  with a ``Retry-After`` header — from the engine's backlog estimate
  (payload ``deadline_s``) and from the proxy's capacity probe
  (``x-deadline-s`` header) — instead of queueing or hanging.

Kills here are deliberate SIGKILL (no cleanup, no goodbye) — the same
brutality as ``_private/chaos.ResourceKiller``.
"""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import pytest

import jax

import ray_tpu
from ray_tpu import serve
from ray_tpu._private import chaos
from ray_tpu.llm import EngineConfig, LLMEngine, SamplingParams
from ray_tpu.models.gptj import GPTJConfig, gptj_init

# seq_len must cover prompt + the longest generation; the paged table
# (max_blocks_per_seq * block_size = 256) is the binding cap
TINY = GPTJConfig(
    vocab_size=128, seq_len=260, d_model=32, n_layers=2, n_heads=2,
    rotary_dim=8, dtype="float32", remat=False, attn_impl="xla",
    fused_loss=False,
)
ECFG = EngineConfig(
    max_slots=2, num_blocks=128, block_size=4, max_blocks_per_seq=64,
    prefill_chunk=8,
)
PROMPT = [5, 6, 7] * 4
DEP = "llm_LLMDeployment"  # app "llm" + default deployment name


@pytest.fixture
def serve_instance():
    ray_tpu.init(num_cpus=8, num_tpus=0)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def reference():
    """Expected token sequences from a local engine with the SAME params
    the replicas build (model seed 0) — the unkilled ground truth."""
    params = gptj_init(jax.random.PRNGKey(0), TINY)
    eng = LLMEngine(TINY, params, ECFG)
    cache: dict = {}

    def ref(sp: SamplingParams) -> list:
        key = (sp.max_tokens, sp.temperature, sp.top_k, sp.top_p, sp.seed)
        if key not in cache:
            cache[key] = eng.generate(PROMPT, sp)
        return cache[key]

    return ref


def _deploy(n_replicas=2, http=False, engine_config=ECFG, max_ongoing=16,
            warmup=True):
    from ray_tpu.serve.llm import build_llm_app

    app = build_llm_app(
        model="gptj", model_cfg=TINY, engine_config=engine_config,
        num_replicas=n_replicas, max_ongoing_requests=max_ongoing,
        warmup=warmup,
    )
    return serve.run(app, name="llm", http=http, http_port=0)


def _kill_active_replica(controller, deadline_s=15.0) -> int:
    """SIGKILL the replica whose engine is actively generating; returns its
    pid. Deterministic chaos: the kill is guaranteed to hit the replica
    serving the in-flight stream."""
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        _, replicas, _ = ray_tpu.get(
            controller.get_replicas.remote(DEP), timeout=10
        )
        for r in replicas:
            st = ray_tpu.get(r.handle_request.remote("stats", (), {}), timeout=10)
            if st["running"] > 0:
                pid = chaos.pid_of_actor(r._actor_id.hex())
                if pid is not None:
                    os.kill(pid, signal.SIGKILL)
                    return pid
        time.sleep(0.01)
    raise AssertionError("no replica was actively generating")


# tier-1 budget (ISSUE 13, tier1-durations on the dev box): 17.8s greedy
# + 16.1s sampled — the serve-chaos-smoke CI job runs this suite in full,
# so the coverage lives there while the 870s tier-1 budget completes
@pytest.mark.slow
@pytest.mark.parametrize(
    "kw",
    [dict(temperature=0.0),
     dict(temperature=0.8, top_k=5, top_p=0.9, seed=123)],
    ids=["greedy", "sampled"],
)
def test_midstream_kill_resumes_token_identical(serve_instance, reference, kw):
    """THE acceptance test: SIGKILL the serving replica mid-generation;
    the stream fails over and completes token-identically."""
    n = 200
    expected = reference(SamplingParams(max_tokens=n, **kw))
    handle = _deploy(n_replicas=2)
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")

    got, killed = [], []
    for tok in handle.options(stream=True).remote(PROMPT, max_tokens=n, **kw):
        got.append(tok)
        if len(got) == 2 and not killed:
            killed.append(_kill_active_replica(controller))
    assert killed, "kill never fired"
    assert len(got) == n
    assert got == expected, (
        f"diverged at {next(i for i, (a, b) in enumerate(zip(got, expected)) if a != b)}"
    )


# tier-1 budget (ISSUE 13): 427.2s on the dev box — HALF the 870s budget
# for one test, and kill/respawn timing also flaked this run; the
# serve-chaos-smoke CI job keeps running it on every push
@pytest.mark.slow
def test_chaos_soak_concurrent_streams_survive_kills(serve_instance, reference):
    """Sustained concurrent streaming while ServeReplicaKiller SIGKILLs
    replicas on a timer: every stream finishes, every token matches."""
    n = 120
    expected = reference(SamplingParams(max_tokens=n))
    # warmup=False: replacement replicas become routable in seconds and
    # compile inside their first request — under churn, a failover must
    # find a successor before the router's pick deadline, and a
    # contended box can't warm a fresh process that fast
    handle = _deploy(n_replicas=2, warmup=False)

    results: list = [None] * 4
    errors: list = []

    def client(i):
        try:
            toks = list(
                handle.options(stream=True).remote(PROMPT, max_tokens=n)
            )
            results[i] = toks
        except Exception as e:  # noqa: BLE001 — the assertion IS "no error"
            errors.append((i, repr(e)))

    with chaos.ServeReplicaKiller(
        deployment=DEP, interval_s=1.5, seed=7, warmup_s=0.4, max_kills=2
    ) as killer:
        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(len(results))
        ]
        for i, t in enumerate(threads):
            t.start()
            if i == 1:
                time.sleep(0.5)  # spread arrivals across the kill window
        # join budget past the replica's 300s stream timeout: on a starved
        # box a stream parked behind replacement-replica jit warmup is
        # SLOW, not hung — a real hang (or stall) still fails, with the
        # EngineStalledError diagnosis in `errors` instead of a bare
        # "thread alive"
        deadline = time.time() + 420
        for t in threads:
            t.join(timeout=max(1.0, deadline - time.time()))
            assert not t.is_alive(), "a stream hung"
    assert killer.kills, "killer never fired — the soak exercised nothing"
    assert not errors, errors
    for i, toks in enumerate(results):
        assert toks == expected, f"stream {i} diverged/truncated"


# tier-1 budget (ISSUE 13): 27.0s measured — serve-chaos-smoke CI covers it
@pytest.mark.slow
def test_controller_kill_during_draining(serve_instance, reference):
    """Kill the CONTROLLER while a replica is draining from a downscale
    and a stream is in flight: the data plane keeps serving (streams
    complete token-identically), and a fresh serve.run recovers."""
    n = 200
    expected = reference(SamplingParams(max_tokens=n))
    handle = _deploy(n_replicas=2)

    # two concurrent streams so both replicas hold in-flight work
    streams = [
        iter(handle.options(stream=True).remote(PROMPT, max_tokens=n))
        for _ in range(2)
    ]
    firsts = [next(s) for s in streams]  # both generating
    # downscale to 1: the excess replica starts DRAINING its stream
    _deploy(n_replicas=1)
    pid = chaos.kill_serve_controller()
    assert pid is not None, "controller kill found no process"

    for first, s in zip(firsts, streams):
        assert [first] + list(s) == expected

    # control plane recovers: a fresh serve.run redeploys and serves
    serve.shutdown()
    handle = _deploy(n_replicas=1)
    assert list(
        handle.options(stream=True).remote(PROMPT, max_tokens=8)
    ) == expected[:8]


def _post(url, body, timeout=300, headers=()):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **dict(headers)},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, resp.read(), dict(resp.headers)


def test_http_deadline_shed_429(serve_instance):
    """Engine-level deadline-aware admission over HTTP: with a measured
    service rate and a deep backlog, a doomed ``deadline_s`` payload gets
    429 + Retry-After instead of queueing; the backlog itself completes."""
    handle = _deploy(
        n_replicas=1,
        engine_config=EngineConfig(
            max_slots=1, num_blocks=128, block_size=4, max_blocks_per_seq=64,
            prefill_chunk=8,
        ),
        http=True,
    )
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    port = ray_tpu.get(controller.get_proxy_port.remote(), timeout=30)
    url = f"http://127.0.0.1:{port}/llm"

    # prime the engine's service-rate estimate
    st, _, _ = _post(url, {"prompt": PROMPT, "max_tokens": 16})
    assert st == 200
    # build a backlog of long generations
    backlog = [
        threading.Thread(
            target=_post, args=(url, {"prompt": PROMPT, "max_tokens": 200}),
            daemon=True,
        )
        for _ in range(4)
    ]
    for t in backlog:
        t.start()
    # the engine never sheds WITHOUT a backlog (an empty engine admits any
    # deadline), so wait until the backlog is actually submitted AND the
    # rate is measured before sending the doomed request
    deadline = time.time() + 30
    while True:
        st_ = handle.stats.remote().result(timeout=30)
        if (
            st_["running"] + st_["waiting"] >= 2
            and st_["service_rate_tokens_per_s"] > 0
        ):
            break
        assert time.time() < deadline, f"backlog never formed: {st_}"
        time.sleep(0.05)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url, {"prompt": PROMPT, "max_tokens": 200, "deadline_s": 0.05})
    assert ei.value.code == 429
    assert int(ei.value.headers["Retry-After"]) >= 1
    for t in backlog:
        t.join(timeout=120)
        assert not t.is_alive(), "backlog request hung"


def test_http_proxy_capacity_shed_429(serve_instance):
    """Proxy-level deadline-aware admission: every replica at its
    admission cap + an ``x-deadline-s`` header = immediate 429, without
    queueing in the router; the same request WITHOUT the header queues
    and succeeds."""
    _deploy(n_replicas=1, max_ongoing=1, http=True)
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    port = ray_tpu.get(controller.get_proxy_port.remote(), timeout=30)
    url = f"http://127.0.0.1:{port}/llm"

    # a slow-consumed stream occupies the single admission slot
    req = urllib.request.Request(
        url, data=json.dumps({"prompt": PROMPT, "max_tokens": 200}).encode(),
        headers={"Content-Type": "application/json"},
    )
    occupier = urllib.request.urlopen(req, timeout=120)
    occupier.read(2)  # headers + first chunk: the slot is held
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(
                url, {"prompt": PROMPT, "max_tokens": 4},
                headers=[("x-deadline-s", "0.2")],
            )
        assert ei.value.code == 429
        assert "Retry-After" in ei.value.headers
    finally:
        occupier.read()  # drain; the slot frees
        occupier.close()
    # no deadline header: the same request queues behind and succeeds
    st, data, _ = _post(url, {"prompt": PROMPT, "max_tokens": 4})
    assert st == 200 and len(data.splitlines()) == 4


# tier-1 budget (ISSUE 13): 12.3s measured — serve-chaos-smoke CI covers it
@pytest.mark.slow
def test_flight_recorder_sees_failover(serve_instance, reference, tmp_path,
                                       monkeypatch):
    """Observability contract: the failover leaves a forensic trail — the
    dead replica's crash-flushed ring on disk and a resumed llm.submit
    (resumed > 0) on the successor."""
    monkeypatch.setenv("RAY_TPU_EVENTS_DIR", str(tmp_path))
    n = 200
    expected = reference(SamplingParams(max_tokens=n))
    handle = _deploy(n_replicas=2)
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")

    from ray_tpu.util import tracing

    with tracing.trace_context() as rid:
        got, killed = [], []
        for tok in handle.options(stream=True).remote(PROMPT, max_tokens=n):
            got.append(tok)
            if len(got) == 2 and not killed:
                killed.append(_kill_active_replica(controller))
    assert got == expected

    from ray_tpu.obs import request_events

    deadline = time.time() + 30
    resumed = []
    while time.time() < deadline and not resumed:
        evs = request_events(rid)
        resumed = [
            e for e in evs
            if e["type"] == "llm.submit" and e.get("resumed", 0) > 0
        ]
        time.sleep(0.5)
    assert resumed, "no resumed llm.submit event under the request id"
    assert resumed[0]["resumed"] >= 2  # at least the delivered prefix
