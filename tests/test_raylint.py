"""raylint rule fixtures: every shipped rule has at least one true-positive
snippet and one suppressed / non-firing snippet, plus coverage for the
baseline mechanics, the JSON CLI surface and --check-imports."""

import json
import textwrap

import pytest

from ray_tpu._lint import all_rules, run_paths
from ray_tpu._lint import baseline as baseline_mod
from ray_tpu._lint.cli import main as lint_main
from ray_tpu._lint.imports_check import check_imports

ALL_RULE_IDS = {r.id for r in all_rules()}


def lint_snippet(tmp_path, source, name="snippet.py", **kw):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return run_paths([str(f)], **kw)


def rule_ids(violations):
    return [v.rule for v in violations]


def test_rule_registry_complete():
    assert {f"RL{i:03d}" for i in range(1, 25)} <= ALL_RULE_IDS


# --------------------------------------------------------------------- RL001


RL001_POS = """
    import ray_tpu

    @ray_tpu.remote
    def outer(refs):
        return ray_tpu.get(refs)
"""


def test_rl001_fires(tmp_path):
    assert "RL001" in rule_ids(lint_snippet(tmp_path, RL001_POS))


def test_rl001_timeout_ok(tmp_path):
    src = """
        import ray_tpu

        @ray_tpu.remote
        def outer(refs):
            return ray_tpu.get(refs, timeout=30)
    """
    assert "RL001" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl001_result_in_actor_method(tmp_path):
    src = """
        class PoolActor:
            def run(self, fut):
                return fut.result()
    """
    assert "RL001" in rule_ids(lint_snippet(tmp_path, src))


def test_rl001_plain_function_ok(tmp_path):
    src = """
        import ray_tpu

        def driver_side(refs):
            return ray_tpu.get(refs)
    """
    assert "RL001" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl001_suppressed(tmp_path):
    src = """
        import ray_tpu

        @ray_tpu.remote
        def outer(refs):
            return ray_tpu.get(refs)  # raylint: disable=RL001
    """
    assert "RL001" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl001_suppressed_on_multiline_call(tmp_path):
    # the disable may sit on any line of the call, incl. the closing paren
    src = """
        import ray_tpu

        @ray_tpu.remote
        def outer(refs):
            return ray_tpu.get(
                refs,
            )  # raylint: disable=RL001
    """
    assert "RL001" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl001_no_duplicate_for_nested_remote_def(tmp_path):
    src = """
        import ray_tpu

        class DriverActor:
            def run(self, ref):
                @ray_tpu.remote
                def inner():
                    return ray_tpu.get(ref)

                return inner.remote()
    """
    assert rule_ids(lint_snippet(tmp_path, src)).count("RL001") == 1


# --------------------------------------------------------------------- RL002


def test_rl002_fires(tmp_path):
    src = """
        import time

        class ChatActor:
            async def handle(self, req):
                time.sleep(1.0)
                return req
    """
    vs = lint_snippet(tmp_path, src)
    assert "RL002" in rule_ids(vs)
    assert "asyncio.sleep" in next(v for v in vs if v.rule == "RL002").message


def test_rl002_sync_method_ok(tmp_path):
    src = """
        import time

        class ChatActor:
            def handle(self, req):
                time.sleep(1.0)
                return req
    """
    assert "RL002" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl002_run_in_executor_remedy_lints_clean(tmp_path):
    # the rule's own recommended fix — blocking call moved into a sync
    # helper handed to run_in_executor — must not itself trigger RL002
    src = """
        import asyncio
        import time

        class ChatActor:
            async def handle(self, req):
                def work():
                    time.sleep(1.0)
                    return req

                return await asyncio.get_event_loop().run_in_executor(None, work)
    """
    assert "RL002" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl002_suppressed_standalone_comment(tmp_path):
    src = """
        import time

        class ChatActor:
            async def handle(self, req):
                # raylint: disable=RL002
                time.sleep(1.0)
                return req
    """
    assert "RL002" not in rule_ids(lint_snippet(tmp_path, src))


# --------------------------------------------------------------------- RL003


RL003_POS = """
    import threading
    import ray_tpu

    lock = threading.Lock()

    @ray_tpu.remote
    def task(x):
        with lock:
            return x
"""


def test_rl003_fires(tmp_path):
    vs = lint_snippet(tmp_path, RL003_POS)
    assert "RL003" in rule_ids(vs)
    assert "threading.Lock" in next(v for v in vs if v.rule == "RL003").message


def test_rl003_local_lock_ok(tmp_path):
    src = """
        import threading
        import ray_tpu

        @ray_tpu.remote
        def task(x):
            lock = threading.Lock()
            with lock:
                return x
    """
    assert "RL003" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl003_param_shadows_ok(tmp_path):
    src = """
        import threading
        import ray_tpu

        lock = threading.Lock()

        @ray_tpu.remote
        def task(lock):
            with lock:
                return 1
    """
    assert "RL003" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl003_suppressed(tmp_path):
    src = """
        import threading
        import ray_tpu

        sock_factory = threading.Lock()

        @ray_tpu.remote
        def task(x):
            return sock_factory  # raylint: disable=RL003
    """
    assert "RL003" not in rule_ids(lint_snippet(tmp_path, src))


# --------------------------------------------------------------------- RL004


def test_rl004_fires_on_actor_method(tmp_path):
    src = """
        class CacheActor:
            def put(self, key, tags=[]):
                return tags
    """
    assert "RL004" in rule_ids(lint_snippet(tmp_path, src))


def test_rl004_fires_on_remote_function(tmp_path):
    src = """
        import ray_tpu

        @ray_tpu.remote
        def task(acc={}):
            return acc
    """
    assert "RL004" in rule_ids(lint_snippet(tmp_path, src))


def test_rl004_plain_class_ok(tmp_path):
    src = """
        class Config:
            def merge(self, overrides={}):
                return overrides
    """
    assert "RL004" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl004_none_default_ok(tmp_path):
    src = """
        class CacheActor:
            def put(self, key, tags=None):
                return tags or []
    """
    assert "RL004" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl004_suppressed(tmp_path):
    src = """
        class CacheActor:
            def put(self, key, tags=[]):  # raylint: disable=RL004
                return tags
    """
    assert "RL004" not in rule_ids(lint_snippet(tmp_path, src))


# --------------------------------------------------------------------- RL005


RL005_POS = """
    class Scheduler:
        def submit(self):
            with self.queue_lock:
                with self.state_lock:
                    pass

        def drain(self):
            with self.state_lock:
                with self.queue_lock:
                    pass
"""


def test_rl005_fires(tmp_path):
    vs = lint_snippet(tmp_path, RL005_POS)
    assert rule_ids(vs).count("RL005") == 1  # one report per lock pair
    assert "ABBA" in vs[0].message or "deadlock" in vs[0].message


def test_rl005_consistent_order_ok(tmp_path):
    src = """
        class Scheduler:
            def submit(self):
                with self.queue_lock:
                    with self.state_lock:
                        pass

            def drain(self):
                with self.queue_lock:
                    with self.state_lock:
                        pass
    """
    assert "RL005" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl005_multi_item_with(tmp_path):
    src = """
        class Scheduler:
            def submit(self):
                with self.a_lock, self.b_lock:
                    pass

            def drain(self):
                with self.b_lock, self.a_lock:
                    pass
    """
    assert "RL005" in rule_ids(lint_snippet(tmp_path, src))


def test_rl005_clock_is_not_a_lock(tmp_path):
    src = """
        class Sim:
            def step(self):
                with self.clock:
                    with self.state_lock:
                        pass

            def reset(self):
                with self.state_lock:
                    with self.clock:
                        pass
    """
    assert "RL005" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl005_suppressed(tmp_path):
    src = """
        class Scheduler:
            def submit(self):
                with self.queue_lock:
                    with self.state_lock:
                        pass

            def drain(self):
                with self.state_lock:
                    with self.queue_lock:  # raylint: disable=RL005
                        pass
    """
    vs = lint_snippet(tmp_path, src)
    # the report anchors on the second-sighted pair's with-statement; either
    # the suppression removed it or the anchor is the outer with of submit —
    # assert that a disable on the reported line works end-to-end
    reported = [v for v in vs if v.rule == "RL005"]
    if reported:  # anchor was not on the suppressed line: move suppression
        line = reported[0].line
        lines = textwrap.dedent(src).splitlines()
        lines[line - 1] += "  # raylint: disable=RL005"
        f = tmp_path / "resupp.py"
        f.write_text("\n".join(lines))
        vs = run_paths([str(f)])
    assert "RL005" not in rule_ids(vs)


# --------------------------------------------------------------------- RL006


def test_rl006_fires_in_hot_path(tmp_path):
    hot = tmp_path / "rl"
    hot.mkdir()
    src = """
        import numpy as np

        def rollout(batches):
            out = []
            for b in batches:
                out.append(np.asarray(b))
            return out
    """
    (hot / "runner.py").write_text(textwrap.dedent(src))
    vs = run_paths([str(tmp_path)])
    assert "RL006" in rule_ids(vs)


def test_rl006_outside_hot_path_ok(tmp_path):
    cold = tmp_path / "misc"
    cold.mkdir()
    src = """
        import numpy as np

        def rollout(batches):
            return [np.asarray(b) for b in batches]
    """
    (cold / "runner.py").write_text(textwrap.dedent(src))
    assert "RL006" not in rule_ids(run_paths([str(tmp_path)]))


def test_rl006_block_until_ready_fires(tmp_path):
    hot = tmp_path / "train"
    hot.mkdir()
    src = """
        def fit(steps, state):
            for _ in range(steps):
                state = step(state)
                state.loss.block_until_ready()
            return state
    """
    (hot / "loop.py").write_text(textwrap.dedent(src))
    assert "RL006" in rule_ids(run_paths([str(tmp_path)]))


def test_rl006_suppressed(tmp_path):
    hot = tmp_path / "ops"
    hot.mkdir()
    src = """
        import numpy as np

        def gather(chunks):
            out = []
            for c in chunks:
                out.append(np.asarray(c))  # raylint: disable=RL006
            return out
    """
    (hot / "mod.py").write_text(textwrap.dedent(src))
    assert "RL006" not in rule_ids(run_paths([str(tmp_path)]))


# --------------------------------------------------------------------- RL007


RL007_POS = """
    def health_loop(self):
        while True:
            try:
                self.tick()
            except Exception:
                pass
"""


def test_rl007_fires(tmp_path):
    assert "RL007" in rule_ids(lint_snippet(tmp_path, RL007_POS))


def test_rl007_outside_loop_ok(tmp_path):
    src = """
        def once(self):
            try:
                self.tick()
            except Exception:
                pass
    """
    assert "RL007" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl007_narrow_except_ok(tmp_path):
    src = """
        def health_loop(self):
            while True:
                try:
                    self.tick()
                except ConnectionError:
                    pass
    """
    assert "RL007" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl007_logged_handler_ok(tmp_path):
    src = """
        def health_loop(self):
            while True:
                try:
                    self.tick()
                except Exception as e:
                    print(f"tick failed: {e!r}")
    """
    assert "RL007" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl007_suppressed(tmp_path):
    src = """
        def teardown(self, workers):
            for w in workers:
                try:
                    w.kill()
                except Exception:  # raylint: disable=RL007
                    pass
    """
    assert "RL007" not in rule_ids(lint_snippet(tmp_path, src))


# --------------------------------------------------------------------- RL008


def test_rl008_fires(tmp_path):
    src = """
        import urllib.request

        class FetcherActor:
            def __init__(self, url):
                self.data = urllib.request.urlopen(url).read()
    """
    assert "RL008" in rule_ids(lint_snippet(tmp_path, src))


def test_rl008_timeout_ok(tmp_path):
    src = """
        import urllib.request

        class FetcherActor:
            def __init__(self, url):
                self.data = urllib.request.urlopen(url, timeout=10).read()
    """
    assert "RL008" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl008_non_actor_ok(tmp_path):
    src = """
        import urllib.request

        class Fetcher:
            def __init__(self, url):
                self.data = urllib.request.urlopen(url).read()
    """
    assert "RL008" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl008_suppressed(tmp_path):
    src = """
        import subprocess

        class BuildActor:
            def __init__(self):
                subprocess.run(["make"])  # raylint: disable=RL008
    """
    assert "RL008" not in rule_ids(lint_snippet(tmp_path, src))


# --------------------------------------------------------------------- RL009


RL009_POS = """
    import jax

    class Runner:
        def __init__(self, params: dict, block_size: int):
            self.params = params
            self.block_size = block_size
            self._step = jax.jit(self._impl, donate_argnums=(0,))

        def _embed(self, tokens):
            return self.params["embed"][tokens]

        def _impl(self, pool, tokens):
            return pool, self._embed(tokens) + self.block_size
"""


def test_rl009_fires_transitively(tmp_path):
    vs = lint_snippet(tmp_path, RL009_POS)
    hits = [v for v in vs if v.rule == "RL009"]
    assert len(hits) == 1  # one report per (function, attribute)
    assert "self.params" in hits[0].message
    assert hits[0].symbol == "Runner._embed"  # the read site, not the jit site
    # static config (int annotation) read in the same traced scope is fine
    assert not any("block_size" in v.message for v in vs)


def test_rl009_decorator_form_fires(tmp_path):
    src = """
        import jax

        class Runner:
            def __init__(self, params: dict):
                self.params = params

            @jax.jit
            def step(self, pool):
                return pool, self.params["w"]
    """
    assert "RL009" in rule_ids(lint_snippet(tmp_path, src))


def test_rl009_partial_decorator_fires(tmp_path):
    src = """
        from functools import partial

        import jax

        WEIGHTS = {}

        @partial(jax.jit, static_argnums=(1,))
        def step(pool, n):
            return pool, WEIGHTS["w"]
    """
    vs = lint_snippet(tmp_path, src)
    assert "RL009" in rule_ids(vs)
    assert "WEIGHTS" in next(v for v in vs if v.rule == "RL009").message


def test_rl009_traced_argument_ok(tmp_path):
    # the fix the rule demands — params threaded through the traced
    # argument — must lint clean (this is model_runner's real shape)
    src = """
        import jax

        class Runner:
            def __init__(self, params: dict, block_size: int):
                self.params = params
                self.block_size = block_size
                self._step = jax.jit(self._impl)

            def _embed(self, params, tokens):
                return params["embed"][tokens]

            def _impl(self, params, pool, tokens):
                return pool, self._embed(params, tokens) + self.block_size

            def step(self, pool, tokens):
                return self._step(self.params, pool, tokens)
    """
    assert "RL009" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl009_unjitted_method_ok(tmp_path):
    src = """
        class Runner:
            def __init__(self, params: dict):
                self.params = params

            def host_side(self, tokens):
                return self.params["embed"][tokens]
    """
    assert "RL009" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl009_suppressed(tmp_path):
    src = RL009_POS.replace(
        'return self.params["embed"][tokens]',
        'return self.params["embed"][tokens]  # raylint: disable=RL009',
    )
    assert "RL009" not in rule_ids(lint_snippet(tmp_path, src))


# --------------------------------------------------------------------- RL010


RL010_CACHE = """
    import threading


    class BlockPool:
        def __init__(self, engine):
            self._lock = threading.Lock()
            self.engine = engine

        def reserve(self):
            with self._lock:
                return self.engine.utilization()

        def free(self):
            with self._lock:
                return 1
"""

RL010_ENGINE = """
    import threading

    from cache import BlockPool


    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self.pool = BlockPool(self)

        def step(self):
            with self._lock:
                self.pool.free()

        def utilization(self):
            with self._lock:
                return 0.5
"""


def write_lock_fixture(tmp_path, cache_src=RL010_CACHE, engine_src=RL010_ENGINE):
    (tmp_path / "cache.py").write_text(textwrap.dedent(cache_src))
    (tmp_path / "engine.py").write_text(textwrap.dedent(engine_src))
    return run_paths([str(tmp_path)])


def test_rl010_cross_module_cycle_fires(tmp_path):
    vs = write_lock_fixture(tmp_path)
    hits = [v for v in vs if v.rule == "RL010"]
    assert len(hits) == 1  # one report per cycle
    msg = hits[0].message
    # both witness paths are cited file:line
    assert "cache.py" in msg and "engine.py" in msg
    assert "Engine._lock" in msg and "BlockPool._lock" in msg


def test_rl010_consistent_order_ok(tmp_path):
    consistent = RL010_CACHE.replace(
        """def reserve(self):
            with self._lock:
                return self.engine.utilization()""",
        """def reserve(self):
            return self.engine.utilization()""",
    )
    vs = write_lock_fixture(tmp_path, cache_src=consistent)
    assert "RL010" not in rule_ids(vs)


RL010_ENGINE_DECLARED = """
    import threading

    from cache import BlockPool

    LOCK_ORDER = ("BlockPool._lock", "Engine._lock")


    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self.pool = BlockPool(self)

        def step(self):
            with self._lock:
                self.pool.free()

        def utilization(self):
            return 0.5
"""


def test_rl010_lock_order_contradiction_fires(tmp_path):
    # no cycle — but an edge against the declared LOCK_ORDER still fires
    vs = write_lock_fixture(tmp_path, engine_src=RL010_ENGINE_DECLARED)
    hits = [v for v in vs if v.rule == "RL010"]
    assert hits and any("contradicts LOCK_ORDER" in v.message for v in hits)


def test_rl010_stale_lock_order_entry_fires(tmp_path):
    engine = RL010_ENGINE_DECLARED.replace(
        'LOCK_ORDER = ("BlockPool._lock", "Engine._lock")',
        'LOCK_ORDER = ("Engine._lock", "BlockPool._lock", "Ghost._lock")',
    )
    vs = write_lock_fixture(tmp_path, engine_src=engine)
    assert any(
        v.rule == "RL010" and "matches no acquisition" in v.message for v in vs
    )


def test_rl010_suppressed(tmp_path):
    vs = write_lock_fixture(tmp_path)
    hits = [v for v in vs if v.rule == "RL010"]
    assert len(hits) == 1
    # suppress on the reported anchor line, wherever the cycle anchored
    target = tmp_path / hits[0].path.split("/")[-1]
    lines = target.read_text().splitlines()
    lines[hits[0].line - 1] += "  # raylint: disable=RL010"
    target.write_text("\n".join(lines))
    assert "RL010" not in rule_ids(run_paths([str(tmp_path)]))


# --------------------------------------------------------------------- RL011


RL011_POS = """
    import threading

    import jax


    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self.watchdog = Watchdog(self)

        def step(self, out):
            with self._lock:
                return jax.device_get(out)


    class Watchdog:
        def __init__(self, engine):
            self.engine = engine
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            with self.engine._lock:
                return self.engine
"""


def test_rl011_fires(tmp_path):
    vs = lint_snippet(tmp_path, RL011_POS)
    hits = [v for v in vs if v.rule == "RL011"]
    assert len(hits) == 1
    assert "jax.device_get" in hits[0].message
    assert "Engine._lock" in hits[0].message
    assert "Watchdog._run" in hits[0].message  # names the monitor path


def test_rl011_bounded_monitor_ok(tmp_path):
    # the watchdog contract: a monitor that only ever takes the lock with
    # a timeout cannot wedge, so the engine's device sync is fine
    src = RL011_POS.replace(
        """def _run(self):
            with self.engine._lock:
                return self.engine""",
        """def _run(self):
            got = self.engine._lock.acquire(timeout=0.1)
            if got:
                self.engine._lock.release()""",
    )
    assert "RL011" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl011_step_loop_owns_its_lock_ok(tmp_path):
    # the lock's ONLY daemon acquirer is the holding function itself (a
    # run_loop daemon driving step()) — the step loop may sync under its
    # own lock; that is what the lock-free beat exists for
    src = """
        import threading

        import jax


        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._thread = threading.Thread(target=self.step, daemon=True)

            def step(self, out=None):
                with self._lock:
                    return jax.device_get(out)
    """
    assert "RL011" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl011_non_daemon_thread_ok(tmp_path):
    # a join()ed non-daemon thread is not a monitor — the rule's contract
    # (and its message) is about daemon/watchdog threads
    src = RL011_POS.replace(
        "threading.Thread(target=self._run, daemon=True)",
        "threading.Thread(target=self._run)",
    )
    assert "RL011" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl011_suppressed(tmp_path):
    src = RL011_POS.replace(
        "return jax.device_get(out)",
        "return jax.device_get(out)  # raylint: disable=RL011",
    )
    assert "RL011" not in rule_ids(lint_snippet(tmp_path, src))


# --------------------------------------------------------------------- RL012


RL012_POS = """
    from ray_tpu._private import events as _events
    from ray_tpu.util.metrics import Counter

    METRIC_NAMES = (
        "widget_hits",
        "widget_ghost",
    )

    hits = Counter("widget_hits", "doc")
    misses = Counter("widget_misses", "doc")
    _events.record("widget.undocumented", n=1)
    panel = "rate(ray_tpu_widget_orphan[1m])"
"""


def test_rl012_all_four_drift_directions(tmp_path):
    vs = lint_snippet(tmp_path, RL012_POS)
    msgs = [v.message for v in vs if v.rule == "RL012"]
    assert len(msgs) == 4
    assert any("widget_ghost" in m and "stale registry" in m for m in msgs)
    assert any("widget_misses" in m and "no METRIC_NAMES" in m for m in msgs)
    assert any("widget.undocumented" in m for m in msgs)
    assert any("widget_orphan" in m and "permanently empty" in m for m in msgs)


def test_rl012_registry_and_emission_consistent_ok(tmp_path):
    src = """
        from ray_tpu.util.metrics import Counter

        METRIC_NAMES = ("widget_hits",)

        hits = Counter("widget_hits", "doc")
    """
    assert "RL012" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl012_collections_counter_is_not_a_metric(tmp_path):
    src = """
        from collections import Counter

        tally = Counter("abc")
    """
    assert "RL012" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl012_suppressed(tmp_path):
    src = RL012_POS.replace(
        'misses = Counter("widget_misses", "doc")',
        'misses = Counter("widget_misses", "doc")  # raylint: disable=RL012',
    ).replace(
        '_events.record("widget.undocumented", n=1)',
        '_events.record("widget.undocumented", n=1)  # raylint: disable=RL012',
    ).replace(
        'panel = "rate(ray_tpu_widget_orphan[1m])"',
        'panel = "rate(ray_tpu_widget_orphan[1m])"  # raylint: disable=RL012',
    ).replace(
        '"widget_ghost",',
        '"widget_hits",',
    )
    vs = lint_snippet(tmp_path, src)
    assert "RL012" not in rule_ids(vs)


# ----------------------------------------------------------------- machinery


def test_syntax_error_reported_not_crash(tmp_path):
    vs = lint_snippet(tmp_path, "def broken(:\n    pass\n")
    assert rule_ids(vs) == ["RL000"]


def test_select_and_ignore(tmp_path):
    src = RL007_POS
    assert rule_ids(lint_snippet(tmp_path, src, select=["RL001"])) == []
    assert rule_ids(lint_snippet(tmp_path, src, ignore=["RL007"])) == []
    assert "RL007" in rule_ids(lint_snippet(tmp_path, src, select=["RL007"]))


def test_unknown_rule_id_is_an_error_not_a_clean_run(tmp_path):
    f = tmp_path / "daemon.py"
    f.write_text(textwrap.dedent(RL007_POS))
    with pytest.raises(ValueError, match="RL999"):
        run_paths([str(f)], select=["RL999"])
    assert lint_main([str(f), "--select", "RL999"]) == 2
    assert lint_main([str(f), "--ignore", "RL07"]) == 2  # typo'd id


def test_disable_all_comment(tmp_path):
    src = """
        def health_loop(self):
            while True:
                try:
                    self.tick()
                except Exception:  # raylint: disable=all
                    pass
    """
    assert rule_ids(lint_snippet(tmp_path, src)) == []


def test_baseline_roundtrip(tmp_path):
    vs = lint_snippet(tmp_path, RL007_POS, name="daemon.py")
    assert vs
    bl_path = tmp_path / "baseline.json"
    baseline_mod.write(bl_path, vs)
    remaining, n_baselined, stale = baseline_mod.apply(vs, baseline_mod.load(bl_path))
    assert remaining == [] and n_baselined == len(vs) and stale == []


def test_baseline_catches_new_violation(tmp_path):
    vs = lint_snippet(tmp_path, RL007_POS, name="daemon.py")
    bl_path = tmp_path / "baseline.json"
    baseline_mod.write(bl_path, vs)
    # add a second swallowing handler in a new function: same file, new symbol
    grown = RL007_POS + """
    def pump_loop(self):
        while True:
            try:
                self.pump()
            except Exception:
                pass
"""
    vs2 = lint_snippet(tmp_path, grown, name="daemon.py")
    remaining, n_baselined, _ = baseline_mod.apply(vs2, baseline_mod.load(bl_path))
    assert n_baselined == len(vs)
    assert [v.symbol for v in remaining] == ["pump_loop"]


def test_baseline_stale_entries_reported(tmp_path):
    vs = lint_snippet(tmp_path, RL007_POS, name="daemon.py")
    bl_path = tmp_path / "baseline.json"
    baseline_mod.write(bl_path, vs)
    clean = lint_snippet(tmp_path, "def fixed():\n    pass\n", name="daemon.py")
    remaining, n_baselined, stale = baseline_mod.apply(clean, baseline_mod.load(bl_path))
    assert remaining == [] and n_baselined == 0 and len(stale) == 1


def test_baseline_partial_burndown_is_stale(tmp_path):
    # count ratchet: an entry whose budget is only partly consumed must be
    # reported stale, or the fixed violations could silently regrow
    two = RL007_POS + """
    def pump_loop(self):
        while True:
            try:
                self.pump()
            except Exception:
                pass
"""
    vs = lint_snippet(tmp_path, two, name="daemon.py")
    assert len(vs) == 2
    bl_path = tmp_path / "baseline.json"
    baseline_mod.write(bl_path, vs)
    one = lint_snippet(tmp_path, RL007_POS, name="daemon.py")
    remaining, n_baselined, stale = baseline_mod.apply(one, baseline_mod.load(bl_path))
    assert remaining == [] and n_baselined == 1
    assert len(stale) == 1 and "pump_loop" in stale[0]


def test_cli_write_baseline_refuses_select(tmp_path, capsys):
    f = tmp_path / "daemon.py"
    f.write_text(textwrap.dedent(RL007_POS))
    bl = tmp_path / "bl.json"
    rc = lint_main([str(f), "--baseline", str(bl), "--write-baseline", "--select", "RL007"])
    assert rc == 2 and not bl.exists()


def test_cli_write_baseline_refuses_partial_scan(tmp_path, capsys):
    # regenerating from a subset of the tree must not drop entries for
    # files the run never scanned
    pkg = tmp_path / "pkg"
    sub = pkg / "sub"
    sub.mkdir(parents=True)
    (pkg / "daemon.py").write_text(textwrap.dedent(RL007_POS))
    (sub / "other.py").write_text(
        textwrap.dedent(RL007_POS).replace("health_loop", "pump_loop")
    )
    bl = tmp_path / "bl.json"
    assert lint_main([str(pkg), "--baseline", str(bl), "--write-baseline"]) == 0
    capsys.readouterr()
    rc = lint_main([str(sub), "--baseline", str(bl), "--write-baseline"])
    assert rc == 2
    assert "pkg/daemon.py" in json.dumps(baseline_mod.load(bl))  # untouched


def test_cli_write_baseline_bootstrap_creates_default(tmp_path, capsys, monkeypatch):
    # the documented adopt-current-state command must work on a checkout
    # with no baseline yet, creating <root parent>/tools/
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "daemon.py").write_text(textwrap.dedent(RL007_POS))
    monkeypatch.chdir(tmp_path)
    assert lint_main(["pkg", "--write-baseline"]) == 0
    assert (tmp_path / "tools" / "raylint-baseline.json").is_file()
    capsys.readouterr()
    assert lint_main(["pkg"]) == 0


def test_overlapping_paths_lint_once(tmp_path):
    pkg = tmp_path / "pkg"
    sub = pkg / "sub"
    sub.mkdir(parents=True)
    (sub / "daemon.py").write_text(textwrap.dedent(RL007_POS))
    vs = run_paths([str(sub), str(pkg)])
    assert rule_ids(vs).count("RL007") == 1


def test_cli_check_imports_rejects_file_arg(tmp_path, capsys):
    f = tmp_path / "mod.py"
    f.write_text("x = 1\n")
    assert lint_main([str(f), "--check-imports"]) == 2


def test_cli_corrupt_baseline_is_usage_error(tmp_path, capsys):
    f = tmp_path / "daemon.py"
    f.write_text(textwrap.dedent(RL007_POS))
    bl = tmp_path / "bl.json"
    bl.write_text("{not json")
    assert lint_main([str(f), "--baseline", str(bl)]) == 2
    assert lint_main([str(f), "--baseline", str(bl), "--write-baseline"]) == 2


def test_default_baseline_found_for_nested_file(tmp_path):
    # linting one nested file must still discover the repo baseline by
    # walking up from the file
    repo = tmp_path / "repo"
    pkg = repo / "pkg" / "sub"
    pkg.mkdir(parents=True)
    (repo / "tools").mkdir()
    (repo / "tools" / "raylint-baseline.json").write_text("{}")
    target = pkg / "mod.py"
    target.write_text("x = 1\n")
    assert (
        baseline_mod.default_baseline_path([str(target)])
        == repo / "tools" / "raylint-baseline.json"
    )


def test_cli_subdir_scan_matches_repo_baseline(tmp_path, capsys, monkeypatch):
    # with the tools/-convention baseline, scanning a subdirectory or a
    # single nested file must fingerprint repo-root-relative and exit 0
    repo = tmp_path / "repo"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    (repo / "tools").mkdir()
    (pkg / "daemon.py").write_text(textwrap.dedent(RL007_POS))
    monkeypatch.chdir(repo)
    bl = repo / "tools" / "raylint-baseline.json"
    assert lint_main(["pkg", "--baseline", str(bl), "--write-baseline"]) == 0
    capsys.readouterr()
    assert lint_main(["pkg"]) == 0  # full scan, default discovery
    assert lint_main([str(pkg / "daemon.py")]) == 0  # nested file
    monkeypatch.chdir(pkg)
    assert lint_main(["daemon.py"]) == 0  # from inside the package
    out = capsys.readouterr().out
    assert "stale" not in out


def test_warn_throttled_never_raises(monkeypatch):
    # the helper runs inside daemon-loop except handlers: a closed stdout
    # pipe (print raising) must not kill the loop it protects
    import builtins

    from ray_tpu._private import log_util

    def broken_print(*a, **k):
        raise BrokenPipeError("stdout gone")

    monkeypatch.setattr(builtins, "print", broken_print)
    log_util.warn_throttled("pipe-test", RuntimeError("x"), interval_s=0.0)


def test_cli_json_output(tmp_path, capsys):
    f = tmp_path / "daemon.py"
    f.write_text(textwrap.dedent(RL007_POS))
    rc = lint_main([str(f), "--format", "json", "--no-baseline"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["violations"][0]["rule"] == "RL007"
    assert out["violations"][0]["symbol"] == "health_loop"


def test_cli_clean_exit_zero(tmp_path, capsys):
    f = tmp_path / "ok.py"
    f.write_text("def fine():\n    return 1\n")
    assert lint_main([str(f)]) == 0


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "daemon.py").write_text(textwrap.dedent(RL007_POS))
    bl = tmp_path / "bl.json"
    assert lint_main([str(pkg), "--baseline", str(bl), "--write-baseline"]) == 0
    capsys.readouterr()
    assert lint_main([str(pkg), "--baseline", str(bl)]) == 0


# ------------------------------------------------------------- check-imports


def _write_pkg(tmp_path, files):
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "__init__.py").write_text("")
    for name, src in files.items():
        (root / name).write_text(textwrap.dedent(src))
    return root


def test_check_imports_clean(tmp_path):
    root = _write_pkg(
        tmp_path,
        {"a.py": "import pkg.b\n", "b.py": "x = 1\n"},
    )
    assert check_imports([str(root)]) == []


def test_check_imports_detects_cycle(tmp_path):
    root = _write_pkg(
        tmp_path,
        {"a.py": "import pkg.b\n", "b.py": "import pkg.a\n"},
    )
    problems = check_imports([str(root)])
    assert len(problems) == 1
    assert "cycle" in problems[0] and "pkg.a" in problems[0] and "pkg.b" in problems[0]


def test_check_imports_function_local_import_breaks_cycle(tmp_path):
    root = _write_pkg(
        tmp_path,
        {
            "a.py": "import pkg.b\n",
            "b.py": "def late():\n    import pkg.a\n",
        },
    )
    assert check_imports([str(root)]) == []


def test_check_imports_from_import_submodule_not_package(tmp_path):
    # `from pkg import b` must create an edge to pkg.b, not to pkg itself —
    # otherwise every package-init import of a submodule looks like a cycle
    root = _write_pkg(
        tmp_path,
        {"a.py": "from pkg import b\n", "b.py": "x = 1\n"},
    )
    (root / "__init__.py").write_text("from pkg import a\n")
    assert check_imports([str(root)]) == []


def test_check_imports_cycle_through_parent_package_init(tmp_path):
    # `import pkg.b.c` also executes pkg/b/__init__.py, so a cycle routed
    # through that __init__ is real even though no module imports it by name
    root = tmp_path / "pkg"
    (root / "b").mkdir(parents=True)
    (root / "__init__.py").write_text("")
    (root / "a.py").write_text("import pkg.b.c\n")
    (root / "b" / "__init__.py").write_text("import pkg.a\n")
    (root / "b" / "c.py").write_text("x = 1\n")
    problems = check_imports([str(root)])
    assert len(problems) == 1 and "pkg.a" in problems[0] and "pkg.b" in problems[0]


def test_check_imports_sibling_via_own_package_ok(tmp_path):
    # importing a sibling submodule must not create an edge onto the
    # importer's own ancestor package (it is already mid-execution) — the
    # ubiquitous `from pkg import sibling` pattern is not a cycle
    root = _write_pkg(
        tmp_path,
        {"a.py": "from pkg import b\n", "b.py": "from pkg import c\n", "c.py": "x = 1\n"},
    )
    (root / "__init__.py").write_text("from pkg import a\n")
    assert check_imports([str(root)]) == []


def test_check_imports_reports_syntax_error(tmp_path):
    root = _write_pkg(tmp_path, {"bad.py": "def broken(:\n"})
    problems = check_imports([str(root)])
    assert any("compile error" in p for p in problems)


def test_check_imports_leaves_no_pycache(tmp_path):
    # the check must not mutate the scanned tree (read-only checkouts)
    root = _write_pkg(tmp_path, {"a.py": "x = 1\n"})
    assert check_imports([str(root)]) == []
    assert not list(root.rglob("__pycache__"))


def test_check_imports_relative_import_cycle(tmp_path):
    root = _write_pkg(
        tmp_path,
        {"a.py": "from . import b\n", "b.py": "from .a import thing\n"},
    )
    problems = check_imports([str(root)])
    assert len(problems) == 1 and "cycle" in problems[0]


# --------------------------------------------------------------------- RL013


RL013_RUNNER = """
    import jax


    class Runner:
        def __init__(self, params):
            self.params = params
            self._decode = jax.jit(self._impl, donate_argnums=(1, 2))

        def _impl(self, params, k_pool, v_pool, tokens):
            return k_pool, v_pool, tokens

        def decode_step(self, k_pool, v_pool, tokens):
            return self._decode(self.params, k_pool, v_pool, tokens)
"""

RL013_ENGINE_BAD = """
    from runner import Runner


    class Engine:
        def __init__(self, pool):
            self.runner = Runner({})
            self.pool = pool

        def step(self, tokens):
            k, v, out = self.runner.decode_step(self.pool.k, self.pool.v, tokens)
            stale = self.pool.k.sum()
            self.pool.k, self.pool.v = k, v
            return out, stale
"""


def write_donation_fixture(tmp_path, engine_src=RL013_ENGINE_BAD):
    (tmp_path / "runner.py").write_text(textwrap.dedent(RL013_RUNNER))
    (tmp_path / "engine.py").write_text(textwrap.dedent(engine_src))
    return run_paths([str(tmp_path)])


def test_rl013_fires_across_modules(tmp_path):
    vs = write_donation_fixture(tmp_path)
    hits = [v for v in vs if v.rule == "RL013"]
    assert len(hits) == 1
    msg = hits[0].message
    # names the poisoned chain, the donating callee and the jit site
    assert "self.pool.k" in msg and "decode_step" in msg
    assert hits[0].symbol == "Engine.step"


def test_rl013_reassign_before_read_ok(tmp_path):
    good = RL013_ENGINE_BAD.replace(
        """k, v, out = self.runner.decode_step(self.pool.k, self.pool.v, tokens)
            stale = self.pool.k.sum()
            self.pool.k, self.pool.v = k, v""",
        """k, v, out = self.runner.decode_step(self.pool.k, self.pool.v, tokens)
            self.pool.k, self.pool.v = k, v
            stale = self.pool.k.sum()""",
    )
    assert "RL013" not in rule_ids(write_donation_fixture(tmp_path, good))


def test_rl013_same_statement_swap_ok(tmp_path):
    # the engine's real idiom: donate and reassign in ONE statement, in a
    # loop — the back edge must see the cleansed state
    good = RL013_ENGINE_BAD.replace(
        """k, v, out = self.runner.decode_step(self.pool.k, self.pool.v, tokens)
            stale = self.pool.k.sum()
            self.pool.k, self.pool.v = k, v
            return out, stale""",
        """for t in tokens:
                self.pool.k, self.pool.v, t = self.runner.decode_step(
                    self.pool.k, self.pool.v, t
                )
            return tokens, 0""",
    )
    assert "RL013" not in rule_ids(write_donation_fixture(tmp_path, good))


def test_rl013_direct_jit_local_fires(tmp_path):
    src = """
        import jax

        def run(state, batch):
            step = jax.jit(lambda s, b: s, donate_argnums=(0,))
            new_state = step(state, batch)
            return state.loss, new_state
    """
    vs = lint_snippet(tmp_path, src)
    hits = [v for v in vs if v.rule == "RL013"]
    assert len(hits) == 1 and "state" in hits[0].message


def test_rl013_branch_read_fires(tmp_path):
    # poisoned on SOME path is enough (may-join): the read sits after a
    # rejoin where only one branch donated
    src = """
        import jax

        def run(state, batch, flip):
            step = jax.jit(lambda s, b: s, donate_argnums=(0,))
            if flip:
                out = step(state, batch)
            else:
                out = state
            return state.loss, out
    """
    assert "RL013" in rule_ids(lint_snippet(tmp_path, src))


def test_rl013_suppressed(tmp_path):
    bad = RL013_ENGINE_BAD.replace(
        "stale = self.pool.k.sum()",
        "stale = self.pool.k.sum()  # raylint: disable=RL013",
    )
    assert "RL013" not in rule_ids(write_donation_fixture(tmp_path, bad))


# --------------------------------------------------------------------- RL014


RL014_POS = """
    import jax

    step = jax.jit(lambda x: x, static_argnums=(1,))


    def drive(xs):
        out = []
        for n, x in enumerate(xs):
            out.append(step(x, n))
        return out
"""


def test_rl014_static_arg_varies_fires(tmp_path):
    vs = lint_snippet(tmp_path, RL014_POS)
    hits = [v for v in vs if v.rule == "RL014"]
    assert len(hits) == 1
    assert "static arg 1" in hits[0].message and "'n'" in hits[0].message


def test_rl014_loop_invariant_static_ok(tmp_path):
    src = RL014_POS.replace("step(x, n)", "step(x, 7)")
    assert "RL014" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl014_static_argname_varies_fires(tmp_path):
    src = """
        import jax

        class R:
            def __init__(self):
                self._p = jax.jit(self._impl, static_argnames=("chunk",))

            def _impl(self, tokens, *, chunk):
                return tokens

            def run(self, pieces):
                out = []
                for piece in pieces:
                    out.append(self._p(piece, chunk=len(piece)))
                return out
    """
    vs = lint_snippet(tmp_path, src)
    assert any(
        v.rule == "RL014" and "'chunk'" in v.message for v in vs
    )


def test_rl014_set_built_pytree_fires(tmp_path):
    src = """
        import jax

        step = jax.jit(lambda tree: tree)

        def drive(names, xs):
            out = []
            for x in xs:
                out.append(step({k: x for k in set(names)}))
            return out
    """
    vs = lint_snippet(tmp_path, src)
    assert any(
        v.rule == "RL014" and "iterating a set" in v.message for v in vs
    )


def test_rl014_not_in_loop_ok(tmp_path):
    src = """
        import jax

        step = jax.jit(lambda x: x, static_argnums=(1,))

        def drive(x, n):
            return step(x, n)
    """
    assert "RL014" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl014_suppressed(tmp_path):
    src = RL014_POS.replace(
        "out.append(step(x, n))",
        "out.append(step(x, n))  # raylint: disable=RL014",
    )
    assert "RL014" not in rule_ids(lint_snippet(tmp_path, src))


# --------------------------------------------------------------------- RL015


RL015_POS = """
    class Scheduler:
        def __init__(self, pool):
            self.pool = pool
            self.slots = {}
            self.waiting = []

        def admit(self, req, free):
            self.waiting.pop(0)
            blocks = self.pool.allocate(req.id, 64)
            slot = free[0]
            self.slots[slot] = req
            return blocks
"""


def test_rl015_exception_path_fires(tmp_path):
    vs = lint_snippet(tmp_path, RL015_POS)
    hits = [v for v in vs if v.rule == "RL015"]
    assert len(hits) == 1
    msg = hits[0].message
    assert "allocate" in msg and "exception path" in msg
    assert hits[0].symbol == "Scheduler.admit"


def test_rl015_release_in_handler_ok(tmp_path):
    src = RL015_POS.replace(
        """blocks = self.pool.allocate(req.id, 64)
            slot = free[0]
            self.slots[slot] = req""",
        """blocks = self.pool.allocate(req.id, 64)
            try:
                slot = free[0]
                self.slots[slot] = req
            except BaseException:
                self.pool.free(req.id)
                raise""",
    )
    assert "RL015" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl015_transfer_before_risk_ok(tmp_path):
    src = RL015_POS.replace(
        """blocks = self.pool.allocate(req.id, 64)
            slot = free[0]
            self.slots[slot] = req""",
        """slot = free[0]
            blocks = self.pool.allocate(req.id, 64)
            self.slots[slot] = req""",
    )
    assert "RL015" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl015_never_resolved_reaches_return_fires(tmp_path):
    src = """
        class C:
            def __init__(self, pool):
                self.pool = pool

            def leak(self, req):
                self.pool.allocate(req.id, 64)
                return True
    """
    vs = lint_snippet(tmp_path, src)
    assert any(
        v.rule == "RL015" and "reaches a return" in v.message for v in vs
    )


def test_rl015_conditional_retain_break_ok(tmp_path):
    # `if not pool.cache_retain(b): break` — the break path did NOT
    # acquire; only the success branch carries the reference
    src = """
        class Cache:
            def __init__(self, pool):
                self.pool = pool
                self.by_block = {}

            def insert(self, blocks):
                for blk in blocks:
                    if not self.pool.cache_retain(blk):
                        break
                    self.by_block[blk] = True
                return len(self.by_block)
    """
    assert "RL015" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl015_raising_call_before_register_fires(tmp_path):
    src = """
        class Cache:
            def __init__(self, pool):
                self.pool = pool
                self.by_block = {}

            def insert(self, key, blk, parent):
                if not self.pool.cache_retain(blk):
                    return None
                node = make_node(key, blk, parent)
                self.by_block[blk] = node
                return node
    """
    vs = lint_snippet(tmp_path, src)
    hits = [v for v in vs if v.rule == "RL015"]
    assert len(hits) == 1 and "cache_retain" in hits[0].message


def test_rl015_suppressed(tmp_path):
    src = RL015_POS.replace(
        "blocks = self.pool.allocate(req.id, 64)",
        "blocks = self.pool.allocate(req.id, 64)  # raylint: disable=RL015",
    )
    assert "RL015" not in rule_ids(lint_snippet(tmp_path, src))


# --------------------------------------------------------------------- RL016


RL016_POS = """
    import faulthandler
    import signal


    def arm(path):
        f = open(path, "w")
        faulthandler.register(signal.SIGUSR1, file=f)
        return path
"""


def test_rl016_open_escapes_on_raise(tmp_path):
    # faulthandler.register can raise; f leaks. (The register call is
    # ALSO the handoff — the leak window is exactly that one statement.)
    vs = lint_snippet(tmp_path, RL016_POS)
    hits = [v for v in vs if v.rule == "RL016"]
    assert len(hits) == 1
    assert "open()" in hits[0].message


def test_rl016_close_on_exception_path_ok(tmp_path):
    src = RL016_POS.replace(
        """f = open(path, "w")
        faulthandler.register(signal.SIGUSR1, file=f)""",
        """f = open(path, "w")
        try:
            faulthandler.register(signal.SIGUSR1, file=f)
        except BaseException:
            f.close()
            raise""",
    )
    assert "RL016" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl016_with_statement_ok(tmp_path):
    src = """
        def read(path):
            with open(path) as f:
                return parse(f.read())
    """
    assert "RL016" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl016_finally_release_ok(tmp_path):
    src = """
        import socket

        def probe(conn):
            s = socket.socket(fileno=conn.fileno())
            try:
                return s.getsockname()[0]
            finally:
                s.close()
    """
    assert "RL016" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl016_unconditional_lock_acquire_fires(tmp_path):
    src = """
        class Pump:
            def drain(self, items):
                self._lock.acquire()
                flush(items)
                self._lock.release()
    """
    vs = lint_snippet(tmp_path, src)
    hits = [v for v in vs if v.rule == "RL016"]
    assert len(hits) == 1 and ".acquire()" in hits[0].message


def test_rl016_bounded_acquire_skipped(tmp_path):
    # conditional ownership (blocking=False / timeout=) is out of scope —
    # boolean-correlated release patterns are RL011's territory
    src = """
        class Pump:
            def drain(self, items):
                if not self._lock.acquire(timeout=0.1):
                    return
                flush(items)
                self._lock.release()
    """
    assert "RL016" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl016_normal_exit_lifetime_resource_ok(tmp_path):
    # only RAISING escapes fire: a deliberately process-lifetime resource
    # handed off by a plain store (which cannot raise) lints clean even
    # though nothing ever closes it
    src = """
        class Arm:
            def arm(self, path):
                f = open(path, "w")
                self.f = f
                return path
    """
    assert "RL016" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl016_suppressed(tmp_path):
    src = RL016_POS.replace(
        'f = open(path, "w")',
        'f = open(path, "w")  # raylint: disable=RL016',
    )
    assert "RL016" not in rule_ids(lint_snippet(tmp_path, src))


# ------------------------------------------------------ --changed-only


def test_report_only_filters_but_keeps_whole_program_index(tmp_path):
    # the index still covers runner.py (RL013 needs its jit registry),
    # but only engine.py may report. report_only takes resolved ABSOLUTE
    # paths — display conventions vary with baseline anchoring, and a
    # mismatch would silently report clean
    (tmp_path / "runner.py").write_text(textwrap.dedent(RL013_RUNNER))
    (tmp_path / "engine.py").write_text(textwrap.dedent(RL013_ENGINE_BAD))
    vs = run_paths(
        [str(tmp_path)], report_only={(tmp_path / "engine.py").resolve()}
    )
    assert rule_ids(vs).count("RL013") == 1
    vs = run_paths(
        [str(tmp_path)], report_only={(tmp_path / "runner.py").resolve()}
    )
    assert "RL013" not in rule_ids(vs)


def test_changed_only_cli_no_git_falls_back(tmp_path, capsys):
    f = tmp_path / "clean.py"
    f.write_text("x = 1\n")
    rc = lint_main([str(tmp_path), "--changed-only"])
    captured = capsys.readouterr()
    # tmp_path is not a git repo: must FALL BACK to a full run (linting
    # nothing and reporting clean would be a false bill of health)
    assert rc == 0 and "linting everything" in captured.err


def test_changed_only_bad_base_ref_falls_back(tmp_path, capsys):
    # a --changed-base that git cannot resolve (shallow clone, typo'd
    # ref) must invalidate the whole fast path, not silently shrink the
    # changed set — a PR gate that checked nothing would read as green
    import subprocess

    def git(*args):
        subprocess.run(
            ["git", *args], cwd=tmp_path, check=True, capture_output=True
        )

    git("init", "-q")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\n\nclass XActor:\n    async def h(self):\n"
        "        time.sleep(1)\n"
    )
    git("add", "-A")
    git("commit", "-qm", "base")  # violation is COMMITTED, tree clean
    rc = lint_main([str(tmp_path), "--changed-only",
                    "--changed-base", "origin/doesnotexist"])
    captured = capsys.readouterr()
    assert "linting everything" in captured.err
    assert rc == 1  # the full-run fallback still sees the RL002


def test_rl016_bound_then_with_ok(tmp_path):
    # `f = open(path)` handed to a with-statement: __exit__ guarantees the
    # close on every path — the standard idiom must not need a suppression
    src = """
        def read(path):
            f = open(path)
            with f:
                return parse(f.read())
    """
    assert "RL016" not in rule_ids(lint_snippet(tmp_path, src))


def test_changed_only_survives_git_quoted_filenames(tmp_path):
    # git's default core.quotePath C-quotes non-ASCII names; a dropped
    # file here would mean a silent false clean on the PR fast path
    import subprocess

    def git(*args):
        subprocess.run(
            ["git", *args], cwd=tmp_path, check=True, capture_output=True
        )

    git("init", "-q")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    git("commit", "-q", "--allow-empty", "-m", "base")
    (tmp_path / "naïve.py").write_text("x = 1\n")
    from ray_tpu._lint.cli import _git_changed_files

    changed = _git_changed_files(tmp_path, None)
    assert changed is not None
    assert any(p.name == "naïve.py" for p in changed), changed


def test_rl014_comprehension_loop_fires(tmp_path):
    # a comprehension is a loop too: the generator target varies per
    # element exactly like a for-statement's
    src = """
        import jax

        step = jax.jit(lambda x: x, static_argnums=(1,))

        def drive(xs):
            return [step(x, n) for n, x in enumerate(xs)]
    """
    vs = lint_snippet(tmp_path, src)
    assert any(v.rule == "RL014" and "'n'" in v.message for v in vs)


# --------------------------------------------------------------------- RL017


RL017_POS = """
    import threading

    class Window:
        def __init__(self):
            self.credits = 0
            self._t = threading.Thread(target=self._drain, daemon=True)
            self._t2 = threading.Thread(target=self._fill, daemon=True)

        def _drain(self):
            self.credits -= 1

        def _fill(self):
            self.credits += 1
"""


def test_rl017_unguarded_counter_two_threads_fires(tmp_path):
    vs = lint_snippet(tmp_path, RL017_POS)
    hits = [v for v in vs if v.rule == "RL017"]
    assert hits and "Window.credits" in hits[0].message
    # both witness roots are named with file:line anchors
    assert "thread:Window._drain" in hits[0].message
    assert "thread:Window._fill" in hits[0].message


def test_rl017_common_lock_ok(tmp_path):
    src = """
        import threading

        class Window:
            def __init__(self):
                self._lock = threading.Lock()
                self.credits = 0
                self._t = threading.Thread(target=self._drain, daemon=True)
                self._t2 = threading.Thread(target=self._fill, daemon=True)

            def _drain(self):
                with self._lock:
                    self.credits -= 1

            def _fill(self):
                with self._lock:
                    self.credits += 1
    """
    assert "RL017" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl017_lock_via_acquire_release_ok(tmp_path):
    # the try/finally .acquire()/.release() idiom guards like a with
    src = """
        import threading

        class Window:
            def __init__(self):
                self._lock = threading.Lock()
                self.credits = 0
                self._t = threading.Thread(target=self._drain, daemon=True)
                self._t2 = threading.Thread(target=self._fill, daemon=True)

            def _drain(self):
                self._lock.acquire()
                try:
                    self.credits -= 1
                finally:
                    self._lock.release()

            def _fill(self):
                self._lock.acquire()
                try:
                    self.credits += 1
                finally:
                    self._lock.release()
    """
    assert "RL017" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl017_plain_flag_store_ok(tmp_path):
    # constant rebinds are GIL-atomic publishes, not corruption
    src = """
        import threading

        class Loop:
            def __init__(self):
                self.running = True
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                while self.running:
                    pass

            def stop(self):
                self.running = False
    """
    assert "RL017" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl017_sync_primitive_attr_ok(tmp_path):
    # Queue/Event attrs are internally synchronized
    src = """
        import queue
        import threading

        class Pump:
            def __init__(self):
                self.q = queue.SimpleQueue()
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                while True:
                    self.q.put(1)

            def feed(self, item):
                self.q.put(item)
    """
    assert "RL017" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl017_single_root_ok(tmp_path):
    # one thread mutating, nothing else touching: no concurrency evidence
    src = """
        import threading

        class Counter:
            def __init__(self):
                self.n = 0
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                self.n += 1
    """
    assert "RL017" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl017_executor_submit_is_a_thread_root(tmp_path):
    src = """
        import threading
        from concurrent.futures import ThreadPoolExecutor

        class Fan:
            def __init__(self):
                self.done = {}
                self.pool = ThreadPoolExecutor(2)
                self._t = threading.Thread(target=self._watch, daemon=True)

            def kick(self, k):
                self.pool.submit(self._work, k)

            def _work(self, k):
                self.done[k] = True

            def _watch(self):
                self.done.clear()
    """
    # pool.submit(self._work) spawns a root: its unguarded dict store
    # conflicts with the watcher thread's clear — without the executor
    # root, _watch alone would be a single root and nothing would fire
    vs = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL017"]
    assert vs and "Fan.done" in vs[0].message
    assert "thread:Fan._work" in vs[0].message


def test_rl017_suppressed(tmp_path):
    src = """
        import threading

        class Window:
            def __init__(self):
                self.credits = 0
                self._t = threading.Thread(target=self._drain, daemon=True)
                self._t2 = threading.Thread(target=self._fill, daemon=True)

            def _drain(self):
                self.credits -= 1  # raylint: disable=RL017

            def _fill(self):
                self.credits += 1  # raylint: disable=RL017
    """
    assert "RL017" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl017_lockfree_declaration_exempts(tmp_path):
    # single-writer counter, declared: the read-side conflict is waived
    src = """
        import threading

        LOCKFREE = ("Killer.kills",)

        class Killer:
            def __init__(self):
                self.kills = 0
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                self.kills += 1

        def stats(k):
            return k.kills
    """
    assert "RL017" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl017_lockfree_stale_entry_fires(tmp_path):
    src = """
        import threading

        LOCKFREE = ("Killer.no_such_attr",)

        class Killer:
            def __init__(self):
                self.kills = 0
    """
    vs = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL017"]
    assert vs and "matches no accessed" in vs[0].message


def test_rl017_lockfree_multiwriter_entry_fires(tmp_path):
    # a bare entry asserts single-writer; two writing roots break it
    src = RL017_POS.replace(
        "import threading",
        'import threading\n\n    LOCKFREE = ("Window.credits",)',
    )
    vs = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL017"]
    assert vs and "declares single-writer" in vs[0].message


def test_rl017_lockfree_atomic_rejects_augassign(tmp_path):
    src = RL017_POS.replace(
        "import threading",
        'import threading\n\n    LOCKFREE = ("Window.credits: atomic",)',
    )
    vs = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL017"]
    assert vs and "read-modify-write" in vs[0].message


def test_rl017_lockfree_atomic_accepts_dict_store(tmp_path):
    src = """
        import threading

        LOCKFREE = ("Registry.rings: atomic",)

        class Registry:
            def __init__(self):
                self.rings = {}
                self._t = threading.Thread(target=self._emit, daemon=True)
                self._t2 = threading.Thread(target=self._fold, daemon=True)

            def _emit(self):
                self.rings[1] = object()

            def _fold(self):
                self.rings.pop(1, None)
    """
    assert "RL017" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl017_lambda_thread_target_resolves(tmp_path):
    src = """
        import threading

        class Beat:
            def __init__(self):
                self.ticks = 0
                self._t = threading.Thread(target=lambda: self._run(), daemon=True)
                self._t2 = threading.Thread(target=self._other, daemon=True)

            def _run(self):
                self.ticks += 1

            def _other(self):
                self.ticks += 1
    """
    vs = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL017"]
    assert vs and "thread:Beat._run" in vs[0].message


# --------------------------------------------------------------------- RL018


RL018_POS = """
    import threading

    class Credits:
        def __init__(self):
            self._lock = threading.Lock()
            self._credits = 0

        def consume(self):
            with self._lock:
                free = self._credits > 0
            if free:
                with self._lock:
                    self._credits -= 1
"""


def test_rl018_check_then_act_fires(tmp_path):
    vs = [v for v in lint_snippet(tmp_path, RL018_POS) if v.rule == "RL018"]
    assert vs and "'_credits'" in vs[0].message
    assert "stale" in vs[0].message


def test_rl018_recheck_under_lock_ok(tmp_path):
    src = """
        import threading

        class Credits:
            def __init__(self):
                self._lock = threading.Lock()
                self._credits = 0

            def consume(self):
                with self._lock:
                    if self._credits > 0:
                        self._credits -= 1
    """
    assert "RL018" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl018_ungated_relock_ok(tmp_path):
    # sequential critical sections with no check feeding the act are the
    # normal re-acquire idiom, not check-then-act
    src = """
        import threading

        class Credits:
            def __init__(self):
                self._lock = threading.Lock()
                self._credits = 0

            def roll(self, n):
                with self._lock:
                    before = self._credits
                with self._lock:
                    self._credits = n
                return before
    """
    assert "RL018" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl018_gate_on_attr_itself_fires(tmp_path):
    src = """
        import threading

        class Credits:
            def __init__(self):
                self._lock = threading.Lock()
                self._credits = 0

            def consume(self):
                with self._lock:
                    probe = self._credits
                if self._credits > 0:
                    with self._lock:
                        self._credits -= 1
    """
    assert "RL018" in rule_ids(lint_snippet(tmp_path, src))


def test_rl018_suppressed(tmp_path):
    src = RL018_POS.replace(
        "with self._lock:\n                    self._credits -= 1",
        "with self._lock:  # raylint: disable=RL018\n"
        "                    self._credits -= 1",
    )
    assert "RL018" not in rule_ids(lint_snippet(tmp_path, src))


# --------------------------------------------------------------------- RL019


def test_rl019_unhandled_kind_fires(tmp_path):
    src = """
        def client(conn):
            conn.send(("ping", 1))
            conn.send(("bye", 0))

        def serve(conn):
            msg = conn.recv()
            if msg[0] == "ping":
                return 1
    """
    vs = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL019"]
    assert len(vs) == 1 and "'bye'" in vs[0].message
    assert "no recv-loop dispatch" in vs[0].message


def test_rl019_unsent_kind_fires(tmp_path):
    src = """
        def client(conn):
            conn.send(("ping", 1))

        def serve(conn):
            msg = conn.recv()
            if msg[0] == "ping":
                return 1
            if msg[0] == "pong":
                return 2
    """
    vs = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL019"]
    assert len(vs) == 1 and "'pong'" in vs[0].message
    assert "dead protocol" in vs[0].message


def test_rl019_param_promoted_handler_ok(tmp_path):
    # the dispatcher pattern: recv loop hands the message to a helper
    src = """
        def serve(conn):
            msg = conn.recv()
            handle(msg)

        def handle(msg):
            kind = msg[0]
            if kind == "ping":
                return 1

        def client(conn):
            conn.send(("ping", 1))
    """
    assert "RL019" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl019_param_send_promoted(tmp_path):
    # the rendezvous pattern: the kind literal lives at the CALLER of a
    # parametric send helper (_broadcast_rendezvous shape)
    src = """
        def broadcast(conn, msg_kind, payload):
            conn.send((msg_kind, payload))

        def rpc_profile(conn):
            broadcast(conn, "profile", {})

        def serve(conn):
            msg = conn.recv()
            if msg[0] == "profile":
                return 1
    """
    assert "RL019" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl019_ternary_and_local_hop_sends(tmp_path):
    # `msg = (...) if .. else (...)` then send(msg): both kinds count
    src = """
        def client(conn, batch):
            msg = ("one", batch[0]) if len(batch) == 1 else ("many", batch)
            conn.send(msg)

        def serve(conn):
            msg = conn.recv()
            if msg[0] == "one":
                return 1
            if msg[0] == "many":
                return 2
    """
    assert "RL019" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl019_non_recv_compare_not_a_handler(tmp_path):
    # locator/spec kind compares are not wire dispatch: with no real
    # handler in view, the send direction is not judged either
    src = """
        def client(conn):
            conn.send(("ping", 1))

        def materialize(locator):
            if locator[0] == "inline":
                return locator[1]
    """
    assert "RL019" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl019_reconnect_sweep_missing_fires(tmp_path):
    src = """
        class Ctx:
            def __init__(self):
                self._submit_buf = []

            def enqueue(self, spec):
                self._submit_buf.append(spec)

            def ship(self, conn):
                conn.send(("submit_batch", self._submit_buf))

        def serve(conn):
            msg = conn.recv()
            if msg[0] == "submit_batch":
                return 1
    """
    vs = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL019"]
    assert len(vs) == 1 and "Ctx._submit_buf" in vs[0].message
    assert "no sweep" in vs[0].message


def test_rl019_reconnect_sweep_present_ok(tmp_path):
    src = """
        class Ctx:
            def __init__(self):
                self._submit_buf = []

            def enqueue(self, spec):
                self._submit_buf.append(spec)

            def ship(self, conn):
                conn.send(("submit_batch", self._submit_buf))

            def _fail_submits(self):
                self._submit_buf = []

        def serve(conn):
            msg = conn.recv()
            if msg[0] == "submit_batch":
                return 1
    """
    assert "RL019" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl019_suppressed(tmp_path):
    src = """
        def client(conn):
            conn.send(("bye", 0))  # raylint: disable=RL019
            conn.send(("ping", 1))

        def serve(conn):
            msg = conn.recv()
            if msg[0] == "ping":
                return 1
    """
    assert "RL019" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl019_data_plane_err_shape_pinned(tmp_path):
    """The true positive RL019 found on its first run over the repo: the
    data-plane client swallowed the server's explicit ("err", reason)
    reply under a catch-all compare, so the kind existed on the wire
    with no named handler. The fixed shape (an explicit == "err"
    branch) lints clean; the pre-fix shape fires."""
    buggy = """
        def fetch(conn):
            conn.send(("fetch", 1))
            resp = conn.recv()
            if resp[0] != "ok":
                raise OSError(resp)
            return resp[1]

        def serve(conn):
            msg = conn.recv()
            if msg[0] == "fetch":
                try:
                    conn.send(("ok", 1))
                except KeyError as e:
                    conn.send(("err", str(e)))
    """
    vs = [v for v in lint_snippet(tmp_path, buggy) if v.rule == "RL019"]
    assert len(vs) == 1 and "'err'" in vs[0].message
    fixed = buggy.replace(
        'if resp[0] != "ok":',
        'if resp[0] == "err":\n'
        "                raise OSError(resp[1])\n"
        '            if resp[0] != "ok":',
    )
    assert "RL019" not in rule_ids(lint_snippet(tmp_path, fixed))


# --------------------------------------------------------------------- RL020


RL020_POS = """
    import jax

    def reduce_grads(g):
        return jax.lax.psum(g, "dp")
"""


def test_rl020_unbound_literal_axis_fires(tmp_path):
    assert "RL020" in rule_ids(lint_snippet(tmp_path, RL020_POS))


def test_rl020_bound_by_shard_map_ok(tmp_path):
    src = """
        import jax
        import numpy as np
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def body(x):
            return jax.lax.psum(x, "dp")

        def outer(x):
            mesh = Mesh(np.array(jax.devices()), ("dp",))
            return shard_map(body, mesh=mesh, in_specs=None, out_specs=None)(x)
    """
    assert "RL020" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl020_opaque_mesh_suppresses(tmp_path):
    # a parameter mesh is unresolvable: the env is ANY and the rule must
    # not invent a finding
    src = """
        import jax
        from jax.experimental.shard_map import shard_map

        def body(x):
            return jax.lax.psum(x, "dp")

        def outer(x, mesh):
            return shard_map(body, mesh=mesh, in_specs=None, out_specs=None)(x)
    """
    assert "RL020" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl020_param_axis_promoted_to_caller(tmp_path):
    src = """
        import jax

        def ring(x, axis_name="sp"):
            return jax.lax.ppermute(x, axis_name, [(0, 1)])

        def caller(x):
            return ring(x, axis_name="tp")
    """
    hits = [
        v for v in lint_snippet(tmp_path, src) if v.rule == "RL020"
    ]
    # fires at the CALLER (both for the literal kwarg and the literal
    # default the bare call relies on), naming the threading path
    assert hits and all(v.symbol == "caller" for v in hits)


def test_rl020_param_axis_dynamic_caller_ok(tmp_path):
    src = """
        import jax

        def ring(x, axis_name="sp"):
            return jax.lax.ppermute(x, axis_name, [(0, 1)])

        def caller(x, ax):
            return ring(x, axis_name=ax)
    """
    # a dynamic axis operand is not promoted — only the default-literal
    # finding for the OMITTED kwarg path may exist, and here the kwarg is
    # always passed, so nothing fires
    assert "RL020" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl020_suppressed(tmp_path):
    src = """
        import jax

        def reduce_grads(g):
            return jax.lax.psum(g, "dp")  # raylint: disable=RL020
    """
    assert "RL020" not in rule_ids(lint_snippet(tmp_path, src))


# --------------------------------------------------------------------- RL021


RL021_AXIS_POS = """
    import jax
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    def body(x):
        return x

    def outer(x):
        mesh = Mesh(np.array(jax.devices()), ("dp", "tp"))
        f = shard_map(body, mesh=mesh, in_specs=(P("fsdp"),), out_specs=P("dp"))
        return f(x)
"""


def test_rl021_spec_axis_not_on_mesh_fires(tmp_path):
    hits = [v for v in lint_snippet(tmp_path, RL021_AXIS_POS) if v.rule == "RL021"]
    assert len(hits) == 1 and "'fsdp'" in hits[0].message


def test_rl021_spec_via_local_name_ok(tmp_path):
    src = """
        import jax
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def body(x):
            return x

        def outer(x):
            mesh = Mesh(np.array(jax.devices()), ("dp", "tp"))
            spec = P(("dp",), "tp")
            f = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec)
            return f(x)
    """
    assert "RL021" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl021_in_specs_arity_fires(tmp_path):
    src = """
        import jax
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def body(x):
            return x

        def outer(x):
            mesh = Mesh(np.array(jax.devices()), ("dp",))
            f = shard_map(body, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P("dp"))
            return f(x, x)
    """
    hits = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL021"]
    assert len(hits) == 1 and "in_specs has 2" in hits[0].message


def test_rl021_arity_respects_partial_and_defaults(tmp_path):
    # ring_attention_sharded's real shape: partial binds axis_name, the
    # remaining 3 required params match 3 specs — must lint clean
    src = """
        import functools
        import jax
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def ring(q, k, v, axis_name="sp"):
            return q

        def sharded(q, k, v):
            mesh = Mesh(np.array(jax.devices()), ("dp", "tp", "sp"))
            spec = P("dp", "tp", "sp")
            f = shard_map(
                functools.partial(ring, axis_name="sp"),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            )
            return f(q, k, v)
    """
    assert "RL021" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl021_named_sharding_axis_fires(tmp_path):
    src = """
        import jax
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        def place(x):
            mesh = Mesh(np.array(jax.devices()), ("dp",))
            return jax.device_put(x, NamedSharding(mesh, P("tp")))
    """
    hits = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL021"]
    assert len(hits) == 1 and "'tp'" in hits[0].message


def test_rl021_placement_rank_fires(tmp_path):
    src = """
        import jax
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        def place(mesh):
            return jax.device_put(np.zeros((4,)), NamedSharding(mesh, P("dp", None)))
    """
    hits = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL021"]
    assert len(hits) == 1 and "rank 1" in hits[0].message


# --------------------------------------------------------------------- RL022


RL022_ARITY_POS = """
    import jax
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    def wrapper(x):
        return pl.pallas_call(
            kernel,
            grid=(4, 4),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((32, 512), "float32"),
        )(x)
"""


def test_rl022_index_map_arity_fires(tmp_path):
    hits = [v for v in lint_snippet(tmp_path, RL022_ARITY_POS) if v.rule == "RL022"]
    assert len(hits) == 1 and "takes 1" in hits[0].message


def test_rl022_scalar_prefetch_widens_arity(tmp_path):
    # PrefetchScalarGridSpec prepends its operands to every index_map:
    # grid rank 1 + 2 prefetch = 3-arg lambdas are CORRECT
    src = """
        import jax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(s, t, x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def wrapper(x):
            grid_spec = pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(4,),
                in_specs=[pl.BlockSpec((1, 8), lambda s, t, i: (i, 0))],
                out_specs=pl.BlockSpec((1, 8), lambda s, t, i: (i, 0)),
            )
            return pl.pallas_call(kernel, grid_spec=grid_spec)(x)
    """
    assert "RL022" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl022_nondividing_out_block_fires(tmp_path):
    src = """
        import jax
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def wrapper(x):
            return pl.pallas_call(
                kernel,
                grid=(4,),
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((20, 128), "float32"),
            )(x)
    """
    hits = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL022"]
    assert len(hits) == 1 and "does not divide" in hits[0].message


def test_rl022_masked_kernel_tail_ok(tmp_path):
    src = """
        import jax
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            @pl.when(pl.program_id(0) < 2)
            def _():
                o_ref[...] = x_ref[...]

        def wrapper(x):
            return pl.pallas_call(
                kernel,
                grid=(4,),
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((20, 128), "float32"),
            )(x)
    """
    assert "RL022" not in rule_ids(lint_snippet(tmp_path, src))


RL022_GATED_SRC = """
    import jax
    from jax.experimental import pallas as pl
    %(registry)s

    def _interp():
        return jax.default_backend() != "tpu"

    def _kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    def _decode_pallas(x):
        return pl.pallas_call(
            _kernel, grid=(4,),
            interpret=_interp(),
        )(x)

    def decode(x):
        if _interp() or x.shape[-1] %% 128:
            return x * 2.0
        return _decode_pallas(x)
"""


def test_rl022_gated_wrapper_undeclared_fires(tmp_path):
    src = RL022_GATED_SRC % {"registry": ""}
    hits = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL022"]
    assert len(hits) == 1 and "INTERPRET_ONLY" in hits[0].message


def test_rl022_gated_wrapper_declared_ok(tmp_path):
    src = RL022_GATED_SRC % {
        "registry": 'INTERPRET_ONLY = ("_decode_pallas: tiling unvalidated on real TPUs",)'
    }
    assert "RL022" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl022_negated_gate_is_not_gated(tmp_path):
    # `if not _interp() and ...: return xla` keeps the pallas path covered
    # wherever the gate is ON (the flash_attention dispatcher shape) — no
    # registry entry required
    src = """
        import jax
        from jax.experimental import pallas as pl

        def _interp():
            return jax.default_backend() != "tpu"

        def _kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def _core_pallas(x):
            return pl.pallas_call(
                _kernel, grid=(4,),
                interpret=_interp(),
            )(x)

        def attention(x):
            if not _interp() and x.shape[-1] % 128:
                return x * 2.0
            return _core_pallas(x)
    """
    assert "RL022" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl022_stale_registry_entry_fires(tmp_path):
    src = """
        INTERPRET_ONLY = ("_old_kernel: long since un-gated",)
    """
    hits = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL022"]
    assert len(hits) == 1 and "matches no interpret-gated" in hits[0].message


def test_rl022_reasonless_entry_fires(tmp_path):
    src = RL022_GATED_SRC % {"registry": 'INTERPRET_ONLY = ("_decode_pallas",)'}
    hits = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL022"]
    assert len(hits) == 1 and "no justification" in hits[0].message


# --------------------------------------------------------------------- RL023


RL023_POS = """
    from jax.experimental.pallas import tpu as pltpu

    def transfer(src, dst, send, recv, n):
        rdma = pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=dst, send_sem=send, recv_sem=recv,
            device_id=n,
        )
        rdma.start()
        check_credit(n)
        rdma.wait()
"""


def test_rl023_raise_path_skips_wait_fires(tmp_path):
    hits = [v for v in lint_snippet(tmp_path, RL023_POS) if v.rule == "RL023"]
    assert len(hits) == 1 and "rdma.start" in hits[0].message


def test_rl023_wait_in_finally_ok(tmp_path):
    src = """
        from jax.experimental.pallas import tpu as pltpu

        def transfer(src, dst, send, recv, n):
            rdma = pltpu.make_async_remote_copy(
                src_ref=src, dst_ref=dst, send_sem=send, recv_sem=recv,
                device_id=n,
            )
            rdma.start()
            try:
                check_credit(n)
            finally:
                rdma.wait()
    """
    assert "RL023" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl023_never_waited_fires_at_start(tmp_path):
    src = """
        from jax.experimental.pallas import tpu as pltpu

        def fire_and_forget(src, dst, send, recv):
            rdma = pltpu.make_async_remote_copy(
                src_ref=src, dst_ref=dst, send_sem=send, recv_sem=recv,
                device_id=1,
            )
            rdma.start()
    """
    hits = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL023"]
    assert len(hits) == 1 and "no path waits" in hits[0].message


def test_rl023_returned_handle_transfers_ownership(tmp_path):
    src = """
        from jax.experimental.pallas import tpu as pltpu

        def start_copy(src, dst, send, recv):
            rdma = pltpu.make_async_remote_copy(
                src_ref=src, dst_ref=dst, send_sem=send, recv_sem=recv,
                device_id=1,
            )
            rdma.start()
            return rdma
    """
    assert "RL023" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl023_split_waits_release(tmp_path):
    # wait_send/wait_recv are the overlap idiom — each counts as release
    src = """
        from jax.experimental.pallas import tpu as pltpu

        def transfer(src, dst, send, recv):
            rdma = pltpu.make_async_remote_copy(
                src_ref=src, dst_ref=dst, send_sem=send, recv_sem=recv,
                device_id=1,
            )
            rdma.start()
            rdma.wait_send()
            rdma.wait_recv()
    """
    assert "RL023" not in rule_ids(lint_snippet(tmp_path, src))


# --------------------------------------------------------------------- RL024


RL024_POS = """
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    def step(p, b):
        return p

    def train(p):
        mesh = Mesh(np.array(jax.devices()), ("dp",))
        step_fn = jax.jit(
            step, in_shardings=(None, NamedSharding(mesh, P("dp"))),
        )
        batch = jax.device_put(np.zeros((8, 4)))
        return step_fn(p, batch)
"""


def test_rl024_default_placement_into_named_slot_fires(tmp_path):
    hits = [v for v in lint_snippet(tmp_path, RL024_POS) if v.rule == "RL024"]
    assert len(hits) == 1
    assert "batch" in hits[0].message and "in_shardings[1]" in hits[0].message


def test_rl024_single_device_sharding_fires(tmp_path):
    src = """
        import jax
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        def step(p, b):
            return p

        def train(p, dev):
            mesh = Mesh(np.array(jax.devices()), ("dp",))
            step_fn = jax.jit(
                step, in_shardings=(None, NamedSharding(mesh, P("dp"))),
            )
            batch = jax.device_put(np.zeros((8, 4)), jax.sharding.SingleDeviceSharding(dev))
            return step_fn(p, batch)
    """
    hits = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL024"]
    assert len(hits) == 1 and "SingleDeviceSharding" in hits[0].message


def test_rl024_matching_placement_ok(tmp_path):
    src = """
        import jax
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        def step(p, b):
            return p

        def train(p):
            mesh = Mesh(np.array(jax.devices()), ("dp",))
            sharding = NamedSharding(mesh, P("dp"))
            step_fn = jax.jit(step, in_shardings=(None, sharding))
            batch = jax.device_put(np.zeros((8, 4)), sharding)
            return step_fn(p, batch)
    """
    assert "RL024" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl024_replacement_clears_drift(tmp_path):
    src = """
        import jax
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        def step(p, b):
            return p

        def train(p):
            mesh = Mesh(np.array(jax.devices()), ("dp",))
            step_fn = jax.jit(
                step, in_shardings=(None, NamedSharding(mesh, P("dp"))),
            )
            batch = jax.device_put(np.zeros((8, 4)))
            batch = jax.device_put(batch, NamedSharding(mesh, P("dp")))
            return step_fn(p, batch)
    """
    assert "RL024" not in rule_ids(lint_snippet(tmp_path, src))


def test_rl024_through_factory_jit(tmp_path):
    # make_step_fn's real shape: the jit site resolves through a factory
    # whose return is directly a jit call
    src = """
        import jax
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        def make_step_fn(mesh):
            def step(p, b):
                return p
            return jax.jit(
                step, in_shardings=(None, NamedSharding(mesh, P("dp"))),
            )

        def train(p):
            mesh = Mesh(np.array(jax.devices()), ("dp",))
            step_fn = make_step_fn(mesh)
            batch = jax.device_put(np.zeros((8, 4)))
            return step_fn(p, batch)
    """
    hits = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL024"]
    assert len(hits) == 1 and "batch" in hits[0].message


# ------------------------------------------------- composition see-through


def test_rl013_sees_through_jit_shard_map_composition(tmp_path):
    # the satellite's point: donation summaries must not go silent on
    # jit(shard_map(f, ...)) — the form the multi-chip engine will use
    src = """
        import jax
        from jax.experimental.shard_map import shard_map

        def step(p, b):
            return p

        def train(p, b, mesh):
            f = jax.jit(
                shard_map(step, mesh=mesh, in_specs=None, out_specs=None),
                donate_argnums=(0,),
            )
            out = f(p, b)
            return p
    """
    hits = [v for v in lint_snippet(tmp_path, src) if v.rule == "RL013"]
    assert len(hits) == 1 and "donated" in hits[0].message
