"""Connectors pipelines + TD3.

Reference counterparts: ``rllib/connectors/`` (env-to-module and
module-to-env transforms), ``rllib/algorithms/td3``.
"""

import numpy as np
import pytest

from ray_tpu.rl.connectors import (
    ClipActions,
    ClipObservations,
    ConnectorPipeline,
    FlattenObservations,
    GaussianActionNoise,
    NormalizeObservations,
)


class TestConnectors:
    def test_flatten_and_clip(self):
        pipe = ConnectorPipeline([FlattenObservations(), ClipObservations(-1, 1)])
        obs = np.full((4, 2, 3), 7.0)
        out = pipe(obs)
        assert out.shape == (4, 6)
        assert (out == 1.0).all()

    def test_normalize_converges_to_unit_scale(self):
        norm = NormalizeObservations()
        rng = np.random.default_rng(0)
        out = None
        for _ in range(50):
            out = norm(rng.normal(5.0, 3.0, size=(64, 4)))
        assert abs(float(out.mean())) < 0.3
        assert 0.5 < float(out.std()) < 1.5

    def test_normalize_state_sync(self):
        a, b = NormalizeObservations(), NormalizeObservations()
        a(np.ones((32, 2)) * 5)
        b.set_state(a.get_state())
        np.testing.assert_allclose(b._mean, a._mean)
        assert b._count == a._count

    def test_clip_actions_and_noise(self):
        clip = ClipActions(low=[-1.0], high=[1.0])
        assert (clip(np.array([[3.0], [-3.0]])) == [[1.0], [-1.0]]).all()
        noise = GaussianActionNoise(0.5, low=-1.0, high=1.0, seed=0)
        out = noise(np.zeros((100, 1)))
        assert out.std() > 0.1 and (np.abs(out) <= 1.0).all()

    def test_runner_applies_connectors(self):
        """Observations reaching the policy (and the batch) are transformed;
        actions reaching the env are transformed."""
        from ray_tpu.rl.env_runner import EnvRunner

        runner = EnvRunner(
            "Pendulum-v1",
            num_envs=2,
            rollout_fragment_length=10,
            seed=0,
            env_to_module_connector=lambda: NormalizeObservations(),
            module_to_env_connector=lambda: ClipActions(low=[-2.0], high=[2.0]),
        )
        batch = runner.sample_transitions(10)
        # normalized observations are clipped to +-10 by default
        assert np.abs(batch["obs"]).max() <= 10.0
        state = runner.get_connector_state()
        assert state["env_to_module"]["count"] > 0
        assert runner.set_connector_state(state)


class TestTD3:
    # tier1-durations: ~12s on the CI box — the full suite overruns the
    # 870s tier-1 budget (truncation, not failures; ROADMAP), so the heaviest
    # non-LLM learning/scale tests run as @slow instead of being cut at random
    @pytest.mark.slow
    def test_td3_trains_and_improves_q(self):
        from ray_tpu.rl.algorithms.td3 import TD3Config

        algo = (
            TD3Config()
            .environment("Pendulum-v1")
            .training(
                learning_starts=300,
                sample_steps_per_iter=300,
                updates_per_iter=50,
                train_batch_size=64,
            )
            .debugging(seed=0)
            .build()
        )
        r1 = algo.train()
        r2 = algo.train()
        assert "learner/q_loss" in r2 and np.isfinite(r2["learner/q_loss"])
        assert r2["buffer_size"] > r1.get("buffer_size", 0) or r2["buffer_size"] > 0

    def test_td3_target_networks_lag(self):
        from ray_tpu.rl.algorithms.td3 import TD3Config

        algo = (
            TD3Config()
            .environment("Pendulum-v1")
            .training(
                learning_starts=100,
                sample_steps_per_iter=150,
                updates_per_iter=30,
                train_batch_size=32,
            )
            .debugging(seed=0)
            .build()
        )
        algo.train()
        p = algo.get_weights()
        # targets must differ from live nets (tau << 1) but not be garbage
        import jax

        d = jax.tree_util.tree_map(
            lambda a, b: float(abs(a - b).max()), p["pi"], p["target_pi"]
        )
        mx = max(jax.tree_util.tree_leaves(d))
        assert 0 < mx < 10.0

    def test_td3_registered(self):
        from ray_tpu.rl import get_algorithm_class

        assert get_algorithm_class("TD3") is not None


class TestDDPG:
    def test_ddpg_single_critic_trains(self):
        """DDPG = TD3 minus the three tricks: the param tree must carry ONE
        critic (no q2/target_q2) and still train to finite losses."""
        from ray_tpu.rl.algorithms.ddpg import DDPGConfig

        algo = (
            DDPGConfig()
            .environment("Pendulum-v1")
            .training(
                learning_starts=200,
                sample_steps_per_iter=250,
                updates_per_iter=40,
                train_batch_size=64,
            )
            .debugging(seed=0)
            .build()
        )
        r = algo.train()
        r = algo.train()
        assert "learner/q_loss" in r and np.isfinite(r["learner/q_loss"])
        p = algo.get_weights()
        assert "q1" in p and "target_q1" in p
        assert "q2" not in p and "target_q2" not in p

    def test_ddpg_actor_updates_every_step(self):
        """policy_delay=1: pi_loss must be non-zero on (virtually) every
        update, unlike TD3 where alternate steps gate it to 0."""
        from ray_tpu.rl.algorithms.ddpg import DDPGConfig

        cfg = DDPGConfig()
        assert cfg.policy_delay == 1 and cfg.target_noise == 0.0
        algo = (
            DDPGConfig()
            .environment("Pendulum-v1")
            .training(
                learning_starts=100,
                sample_steps_per_iter=150,
                updates_per_iter=10,
                train_batch_size=32,
            )
            .debugging(seed=1)
            .build()
        )
        r = algo.train()
        assert r["learner/pi_loss"] != 0.0
