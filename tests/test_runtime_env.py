"""Runtime env tests (reference: ``python/ray/tests/test_runtime_env*.py``
themes: env_vars for tasks/actors, working_dir upload + extraction +
importability)."""

import os

import pytest

import ray_tpu


def test_task_env_vars_scoped(ray_start_regular):
    @ray_tpu.remote
    def read(name):
        return os.environ.get(name)

    with_env = read.options(runtime_env={"env_vars": {"RE_TEST_VAR": "abc"}})
    assert ray_tpu.get(with_env.remote("RE_TEST_VAR"), timeout=60) == "abc"
    # a plain task on the (possibly same, reused) worker must NOT see it
    assert ray_tpu.get(read.remote("RE_TEST_VAR"), timeout=60) is None


def test_actor_env_vars_persist(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_VAR": "on"}})
    class A:
        def read(self):
            return os.environ.get("ACTOR_VAR")

    a = A.remote()
    assert ray_tpu.get(a.read.remote(), timeout=60) == "on"
    assert ray_tpu.get(a.read.remote(), timeout=60) == "on"  # persists


def test_working_dir_ships_and_imports(ray_start_regular, tmp_path):
    pkg = tmp_path / "proj"
    pkg.mkdir()
    (pkg / "helper_mod.py").write_text("MAGIC = 1234\n")
    (pkg / "data.txt").write_text("payload")

    @ray_tpu.remote(runtime_env={"working_dir": str(pkg)})
    def use_dir():
        import helper_mod  # importable from the extracted working_dir

        return helper_mod.MAGIC, open("data.txt").read(), os.path.basename(os.getcwd())

    magic, data, _cwd = ray_tpu.get(use_dir.remote(), timeout=120)
    assert magic == 1234
    assert data == "payload"


def test_runtime_env_validation(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(ValueError, match="Unsupported runtime_env"):
        f.options(runtime_env={"conda": "env"}).remote()
    with pytest.raises(ValueError, match="not a directory"):
        f.options(runtime_env={"working_dir": "/nonexistent/xyz"}).remote()
