"""Runtime env tests (reference: ``python/ray/tests/test_runtime_env*.py``
themes: env_vars for tasks/actors, working_dir upload + extraction +
importability)."""

import os

import pytest

import ray_tpu


def test_task_env_vars_scoped(ray_start_regular):
    @ray_tpu.remote
    def read(name):
        return os.environ.get(name)

    with_env = read.options(runtime_env={"env_vars": {"RE_TEST_VAR": "abc"}})
    assert ray_tpu.get(with_env.remote("RE_TEST_VAR"), timeout=60) == "abc"
    # a plain task on the (possibly same, reused) worker must NOT see it
    assert ray_tpu.get(read.remote("RE_TEST_VAR"), timeout=60) is None


def test_actor_env_vars_persist(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_VAR": "on"}})
    class A:
        def read(self):
            return os.environ.get("ACTOR_VAR")

    a = A.remote()
    assert ray_tpu.get(a.read.remote(), timeout=60) == "on"
    assert ray_tpu.get(a.read.remote(), timeout=60) == "on"  # persists


def test_working_dir_ships_and_imports(ray_start_regular, tmp_path):
    pkg = tmp_path / "proj"
    pkg.mkdir()
    (pkg / "helper_mod.py").write_text("MAGIC = 1234\n")
    (pkg / "data.txt").write_text("payload")

    @ray_tpu.remote(runtime_env={"working_dir": str(pkg)})
    def use_dir():
        import helper_mod  # importable from the extracted working_dir

        return helper_mod.MAGIC, open("data.txt").read(), os.path.basename(os.getcwd())

    magic, data, _cwd = ray_tpu.get(use_dir.remote(), timeout=120)
    assert magic == 1234
    assert data == "payload"


def test_runtime_env_validation(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(ValueError, match="Unsupported runtime_env"):
        f.options(runtime_env={"nonexistent_tier": "x"}).remote()
    with pytest.raises(ValueError, match="not a directory"):
        f.options(runtime_env={"working_dir": "/nonexistent/xyz"}).remote()


# -- py_modules / pip / plugins (reference: runtime_env/{packaging,pip,plugin}.py)


def _write_wheel(path, name="tinypkg", ver="1.0", body="MAGIC = 'hello'"):
    """Hand-built minimal wheel: installable offline with --no-index."""
    import base64
    import hashlib
    import zipfile

    records = []

    def add(zf, arc, data: bytes):
        zf.writestr(arc, data)
        h = base64.urlsafe_b64encode(hashlib.sha256(data).digest()).rstrip(b"=").decode()
        records.append(f"{arc},sha256={h},{len(data)}")

    whl = str(path / f"{name}-{ver}-py3-none-any.whl")
    with zipfile.ZipFile(whl, "w") as zf:
        add(zf, f"{name}/__init__.py", (body + "\n").encode())
        add(
            zf,
            f"{name}-{ver}.dist-info/METADATA",
            f"Metadata-Version: 2.1\nName: {name}\nVersion: {ver}\n".encode(),
        )
        add(
            zf,
            f"{name}-{ver}.dist-info/WHEEL",
            b"Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib: true\nTag: py3-none-any\n",
        )
        rec = f"{name}-{ver}.dist-info/RECORD"
        zf.writestr(rec, "\n".join(records + [f"{rec},,"]) + "\n")
    return whl


def test_pip_env_installs_package_driver_lacks(ray_start_regular, tmp_path):
    whl = _write_wheel(tmp_path, name="rtpxyzpkg", body="MAGIC = 'from-pip-env'")

    with pytest.raises(ImportError):
        import rtpxyzpkg  # noqa: F401 - the DRIVER env must lack it

    @ray_tpu.remote
    class Uses:
        def magic(self):
            import rtpxyzpkg

            return rtpxyzpkg.MAGIC

        def prefix_mtime(self):
            import sys

            prefix = next(p for p in sys.path if "/pip-" in p)
            return prefix, os.path.getmtime(os.path.join(prefix, ".done"))

    a = Uses.options(runtime_env={"pip": [whl]}).remote()
    assert ray_tpu.get(a.magic.remote(), timeout=120) == "from-pip-env"
    prefix1, built1 = ray_tpu.get(a.prefix_mtime.remote(), timeout=30)

    # second actor, same env: the node cache HITS (no rebuild -> same marker)
    b = Uses.options(runtime_env={"pip": [whl]}).remote()
    assert ray_tpu.get(b.magic.remote(), timeout=120) == "from-pip-env"
    prefix2, built2 = ray_tpu.get(b.prefix_mtime.remote(), timeout=30)
    assert prefix1 == prefix2 and built1 == built2


def test_py_modules_ship_and_import(ray_start_regular, tmp_path):
    mod = tmp_path / "shippedmod"
    mod.mkdir()
    (mod / "__init__.py").write_text("VALUE = 41\n")
    (mod / "extra.py").write_text("def bump(x):\n    return x + 1\n")

    @ray_tpu.remote
    def use():
        import shippedmod
        from shippedmod.extra import bump

        return bump(shippedmod.VALUE)

    ref = use.options(runtime_env={"py_modules": [str(mod)]}).remote()
    assert ray_tpu.get(ref, timeout=60) == 42


def test_plugin_seam(tmp_path):
    """The plugin API (reference: runtime_env/plugin.py): package_value at
    submission, apply as a worker-side context manager. Exercised
    in-process (plugins must be registered in the consuming process)."""
    from ray_tpu._private import runtime_env as renv

    events = []

    class StampPlugin(renv.RuntimeEnvPlugin):
        def package_value(self, value, ctx):
            events.append(("package", value))
            return value.upper()

        @__import__("contextlib").contextmanager
        def apply(self, value, ctx):
            os.environ["RTP_PLUGIN_STAMP"] = value
            events.append(("apply", value))
            try:
                yield
            finally:
                os.environ.pop("RTP_PLUGIN_STAMP", None)

    renv.register_plugin("stamp", StampPlugin())
    try:
        class _KV:
            def __init__(self):
                self.kv = {}

            def call(self, method, **kw):
                if method == "kv_get":
                    return self.kv.get(kw["key"])
                if method == "kv_put":
                    self.kv[kw["key"]] = kw["value"]

        ctx = _KV()
        spec = renv.package({"stamp": "abc"}, ctx)
        assert spec["plugins"]["stamp"] == "ABC"
        with renv.applied(spec, ctx):
            assert os.environ.get("RTP_PLUGIN_STAMP") == "ABC"
        assert os.environ.get("RTP_PLUGIN_STAMP") is None
        assert events == [("package", "abc"), ("apply", "ABC")]
    finally:
        renv._PLUGINS.pop("stamp", None)
