"""Speculative decoding: kernel parity, drafters, token identity, sampling.

Coverage demanded by the feature's acceptance criteria:

* multi-query paged verification attention (Pallas interpret mode) == the
  XLA reference to <= 2e-5, and == per-window-index single-position
  decode attention;
* spec-decode greedy output token-identical to dense ``gptj_decode`` for
  BOTH built-in drafters — including under recompute preemption and
  mixed prefill/decode steps — and for the GPT architecture;
* rejection sampling at temperature > 0 reproduces the target filtered
  distribution (fixed seeds, empirical frequencies);
* ledger rollback (``KVBlockPool.shrink_to``) and drafter proposal
  mechanics;
* the serve autoscaler consumes replica-exported ``autoscaling_metrics``
  (queue depth / KV utilization) in its scaling decision.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.llm import (
    CacheConfig,
    EngineConfig,
    KVBlockPool,
    LLMEngine,
    NGramDrafter,
    SamplingParams,
)
from ray_tpu.models.gptj import GPTJConfig, gptj_decode, gptj_init

TINY = GPTJConfig(
    vocab_size=128, seq_len=64, d_model=32, n_layers=2, n_heads=2,
    rotary_dim=8, dtype="float32", remat=False, attn_impl="xla",
    fused_loss=False,
)


@pytest.fixture(scope="module")
def tiny_params():
    return gptj_init(jax.random.PRNGKey(0), TINY)


@pytest.fixture(scope="module")
def spec_engine(tiny_params):
    """One n-gram-drafted engine shared by the identity tests (each fresh
    engine re-jits its step functions; compiles dominate runtime).  Tests
    leave it drained."""
    return _engine(tiny_params, spec_k=3)


def _engine(params, **kw):
    defaults = dict(
        max_slots=3, num_blocks=32, block_size=4, max_blocks_per_seq=12,
        prefill_chunk=8,
    )
    defaults.update(kw)
    return LLMEngine(TINY, params, EngineConfig(**defaults))


def _prompt(n, seed=1):
    return list(np.random.RandomState(seed).randint(0, TINY.vocab_size, n))


def _drive(engine, reqs, timeout=120.0):
    import time

    deadline = time.monotonic() + timeout
    while not all(r.finished for r in reqs):
        engine.step()
        assert time.monotonic() < deadline, "engine did not finish in time"


def _ref_decode(params, prompt, n_new):
    out = gptj_decode(TINY, params, jnp.asarray([prompt], jnp.int32), n_new)
    return [int(t) for t in np.asarray(out)[0, len(prompt):]]


# ---------------------------------------------------------------------------
# multi-query paged verification attention
# ---------------------------------------------------------------------------


class TestPagedVerifyAttention:
    def _case(self, seed=0, slots=3, w=4, heads=4, d=16, blocks=12, bs=4, tmax=6):
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(slots, w, heads, d), jnp.float32)
        kp = jnp.asarray(rng.randn(blocks, heads, bs, d), jnp.float32)
        vp = jnp.asarray(rng.randn(blocks, heads, bs, d), jnp.float32)
        bt = jnp.asarray(rng.randint(0, blocks, (slots, tmax)), jnp.int32)
        base = jnp.asarray(rng.randint(0, tmax * bs - w, slots), jnp.int32)
        pos = base[:, None] + jnp.arange(w)[None, :]
        return q, kp, vp, bt, pos

    def test_pallas_matches_xla(self):
        from ray_tpu.ops.paged_attention import paged_verify_attention

        q, kp, vp, bt, pos = self._case()
        ref = paged_verify_attention(q, kp, vp, bt, pos, impl="xla")
        out = paged_verify_attention(q, kp, vp, bt, pos, impl="pallas")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_pallas_matches_xla_under_jit(self):
        from ray_tpu.ops.paged_attention import paged_verify_attention

        q, kp, vp, bt, pos = self._case(seed=7)
        ref = paged_verify_attention(q, kp, vp, bt, pos, impl="xla")
        out = jax.jit(lambda *a: paged_verify_attention(*a, impl="pallas"))(
            q, kp, vp, bt, pos
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_window_matches_single_position_decode(self):
        """Each window index must equal a single-position paged_attention
        call at that position — the verify op IS w stacked decode steps."""
        from ray_tpu.ops.paged_attention import (
            paged_attention,
            paged_verify_attention,
        )

        q, kp, vp, bt, pos = self._case(seed=3)
        out = paged_verify_attention(q, kp, vp, bt, pos, impl="xla")
        for i in range(q.shape[1]):
            single = paged_attention(
                q[:, i], kp, vp, bt, pos[:, i] + 1, impl="xla"
            )
            np.testing.assert_allclose(
                np.asarray(out[:, i]), np.asarray(single), atol=2e-5
            )

    def test_bad_impl_rejected(self):
        from ray_tpu.ops.paged_attention import paged_verify_attention

        q, kp, vp, bt, pos = self._case()
        with pytest.raises(ValueError, match="unknown paged attention impl"):
            paged_verify_attention(q, kp, vp, bt, pos, impl="cuda")


# ---------------------------------------------------------------------------
# drafters + ledger rollback
# ---------------------------------------------------------------------------


class TestDrafters:
    def test_ngram_locks_onto_period(self):
        d = NGramDrafter(k=4, max_ngram=3)
        ctx = [5, 9, 7, 5, 9, 7, 5, 9]           # period 3, mid-cycle
        assert list(d.propose([ctx])[0]) == [7, 5, 9, 7]

    def test_ngram_extends_past_context_end(self):
        d = NGramDrafter(k=6, max_ngram=2)
        ctx = [1, 2, 1, 2]                        # match at the tail itself
        assert list(d.propose([ctx])[0]) == [1, 2, 1, 2, 1, 2]

    def test_ngram_no_match_repeats_last(self):
        d = NGramDrafter(k=3, max_ngram=3)
        assert list(d.propose([[4, 8, 15, 16, 23]])[0]) == [23, 23, 23]

    def test_ngram_batch_shape(self):
        d = NGramDrafter(k=2)
        out = d.propose([[1, 2], [3, 3, 3]])
        assert out.shape == (2, 2) and out.dtype == np.int32

    def test_ngram_match_confidence(self):
        """``last_matched`` separates real n-gram matches from the
        repeat-last fallback — the engine's skip-verification signal."""
        d = NGramDrafter(k=2, max_ngram=3)
        d.propose([[1, 2, 1, 2], [4, 8, 15, 16], [7, 7]])
        assert list(d.last_matched) == [True, False, True]

    def test_small_model_drafter_static_shape(self, tiny_params):
        from ray_tpu.llm.drafter import SmallModelDrafter

        d = SmallModelDrafter(TINY, tiny_params, k=2, slots=3, ctx_window=8)
        short = d.propose([[1, 2, 3]])
        assert short.shape == (1, 2)
        full = d.propose([list(range(20)), [7] * 4, [1]])
        assert full.shape == (3, 2)
        assert (full >= 0).all() and (full < TINY.vocab_size).all()
        with pytest.raises(ValueError, match="contexts"):
            d.propose([[1]] * 4)

    def test_shrink_to_returns_tail_blocks(self):
        pool = KVBlockPool(
            CacheConfig(num_blocks=9, block_size=4, max_blocks_per_seq=8),
            n_layers=1, n_heads=1, head_dim=4,
        )
        blocks = pool.allocate("a", 20)           # 5 blocks
        assert pool.shrink_to("a", 20) == 0       # nothing to roll back
        assert pool.shrink_to("a", 9) == 2        # keep ceil(9/4) = 3
        assert list(pool.table_row("a")[:3]) == blocks[:3]
        assert pool.num_free_blocks == 5
        # released blocks are immediately reusable, and growth re-extends
        assert pool.grow_to("a", 20) is True
        with pytest.raises(KeyError):
            pool.shrink_to("ghost", 4)


# ---------------------------------------------------------------------------
# engine: greedy spec decode is token-identical to gptj_decode
# ---------------------------------------------------------------------------


class TestSpecEngineGreedyIdentity:
    def test_ngram_drafter_matches_reference(self, tiny_params, spec_engine):
        prompt = _prompt(10)
        out = spec_engine.generate(prompt, SamplingParams(max_tokens=12))
        assert out == _ref_decode(tiny_params, prompt, 12)
        s = spec_engine.stats()
        assert s["spec_proposed"] > 0
        assert s["running"] == 0 and s["kv_utilization"] == 0.0

    def test_mixed_prefill_decode_matches_reference(self, tiny_params, spec_engine):
        """Staggered admissions: new requests chunk-prefill while earlier
        ones speculate; every stream must match its own reference."""
        eng = spec_engine
        prompts = [_prompt(5, seed=2), _prompt(9, seed=3), _prompt(13, seed=4)]
        reqs = [eng.submit(prompts[0], SamplingParams(max_tokens=10))]
        eng.step()  # first request is mid-flight before the others arrive
        reqs += [eng.submit(p, SamplingParams(max_tokens=10)) for p in prompts[1:]]
        _drive(eng, reqs)
        for req, p in zip(reqs, prompts):
            assert req.out == _ref_decode(tiny_params, p, 10)

    def test_stop_token_inside_window(self, tiny_params, spec_engine):
        """A stop token accepted mid-window must end the stream exactly
        there — trailing accepted tokens are discarded, matching what
        sequential decode would have produced."""
        prompt = _prompt(10)
        full = _ref_decode(tiny_params, prompt, 12)
        stop = full[5]
        req = spec_engine.submit(
            prompt, SamplingParams(max_tokens=12, stop_token_ids=(stop,))
        )
        _drive(spec_engine, [req])
        assert req.finish_reason == "stop"
        cut = full.index(stop) + 1
        assert req.out == full[:cut]

    def test_preemption_under_pressure_matches_reference(self, tiny_params):
        """A pool too small for all three completions forces recompute
        preemption mid-speculation; outputs must still match exactly."""
        eng = _engine(
            tiny_params, max_slots=3, num_blocks=13, block_size=4,
            max_blocks_per_seq=10, spec_k=3,
        )
        prompts = [_prompt(8, seed=s) for s in (5, 6, 7)]
        reqs = [eng.submit(p, SamplingParams(max_tokens=16)) for p in prompts]
        _drive(eng, reqs)
        assert eng.stats()["preemptions"] > 0, "pool was sized to force preemption"
        for req, p in zip(reqs, prompts):
            assert req.out == _ref_decode(tiny_params, p, 16)

    def test_model_drafter_matches_reference(self, tiny_params):
        """Small-model drafter (a DIFFERENT random model): acceptance is
        whatever it is, output must be identical — with and without
        preemption pressure."""
        draft_params = gptj_init(jax.random.PRNGKey(42), TINY)
        eng = LLMEngine(
            TINY, tiny_params,
            EngineConfig(
                max_slots=3, num_blocks=13, block_size=4, max_blocks_per_seq=10,
                prefill_chunk=8, spec_k=2, spec_drafter="model",
                spec_draft_ctx=8,
            ),
            draft_model_cfg=TINY, draft_params=draft_params,
        )
        prompts = [_prompt(8, seed=s) for s in (5, 6, 7)]
        reqs = [eng.submit(p, SamplingParams(max_tokens=16)) for p in prompts]
        _drive(eng, reqs)
        assert eng.stats()["preemptions"] > 0
        for req, p in zip(reqs, prompts):
            assert req.out == _ref_decode(tiny_params, p, 16)

    def test_gpt_arch_matches_reference(self):
        """The verify step's GPT branch (learned positions, fused qkv,
        sequential residual): spec output == gpt_decode."""
        from ray_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init

        cfg = GPTConfig(
            vocab_size=96, seq_len=48, d_model=32, n_layers=2, n_heads=2,
            dtype="float32", remat=False, attn_impl="xla", fused_loss=False,
        )
        params = gpt_init(jax.random.PRNGKey(1), cfg)
        eng = LLMEngine(
            cfg, params,
            EngineConfig(
                max_slots=2, num_blocks=16, block_size=4, max_blocks_per_seq=8,
                prefill_chunk=8, spec_k=2,
            ),
        )
        prompt = list(range(7, 17))
        out = eng.generate(prompt, SamplingParams(max_tokens=8))
        ref = gpt_decode(cfg, params, jnp.asarray([prompt], jnp.int32), 8)
        assert out == [int(t) for t in np.asarray(ref)[0, len(prompt):]]

    def test_model_length_cap_inside_window(self, tiny_params):
        """A request whose remaining budget is smaller than the window
        still finishes exactly at max_tokens (surplus acceptance and
        past-the-table provisional writes are discarded)."""
        eng = _engine(tiny_params, spec_k=3)
        prompt = _prompt(10)
        out = eng.generate(prompt, SamplingParams(max_tokens=2))
        assert out == _ref_decode(tiny_params, prompt, 2)

    def test_backoff_engages_on_low_acceptance(self, tiny_params):
        """Random-prompt (hostile) workload: the drafter's confidence
        gate (no n-gram match -> no verify) and the acceptance backoff
        must keep the engine from speculating every step — and the output
        must still match the reference through the mode switches."""
        eng = _engine(tiny_params, spec_k=3)
        prompt = _prompt(12, seed=11)
        out = eng.generate(prompt, SamplingParams(max_tokens=16))
        assert out == _ref_decode(tiny_params, prompt, 16)
        s = eng.stats()
        # with vocab 128 and a random model, drafts almost never match —
        # speculation must not have run every step
        assert s["spec_proposed"] < 3 * 16 * eng.cfg.spec_k

    def test_no_match_gate_skips_verification(self, tiny_params):
        """A context with no n-gram match anywhere must not pay a verify
        step at all: the drafter reports no confidence and the engine
        plain-decodes (output identical, zero proposals)."""
        eng = _engine(tiny_params, spec_k=3)
        prompt = list(range(1, 13))  # strictly increasing: no match ever
        out = eng.generate(prompt, SamplingParams(max_tokens=4))
        assert out == _ref_decode(tiny_params, prompt, 4)
        s = eng.stats()
        # the only verify the engine may have run is warmup's (none here);
        # every step of THIS request must have been gated to plain decode
        # unless the generated tokens themselves created a match
        ctx = prompt + out
        from ray_tpu.llm.drafter import NGramDrafter

        d = NGramDrafter(k=3)
        d.propose([ctx])
        if not d.last_matched[0]:
            assert s["spec_proposed"] == 0


# ---------------------------------------------------------------------------
# rejection sampling (temperature > 0)
# ---------------------------------------------------------------------------


class TestSpeculativeSampling:
    def test_verified_position_reproduces_target_distribution(self):
        """Delta-proposal rejection sampling must reproduce the target
        softmax EXACTLY in distribution, whatever token was drafted:
        empirical frequencies over fixed seeds vs the analytic target."""
        from ray_tpu.models.sampling import speculative_verify

        v = 16
        logits = jnp.asarray(
            np.random.RandomState(0).randn(2, v) * 1.5, jnp.float32
        )
        target = np.asarray(jax.nn.softmax(logits[0]))
        draft_tok = int(np.argmax(target))  # high-prob draft: mostly accepts
        fn = jax.jit(
            lambda s: speculative_verify(
                logits, jnp.asarray([draft_tok], jnp.int32), s,
                jnp.int32(0), temperature=1.0,
            )
        )
        n_trials = 1500
        counts = np.zeros(v)
        accepts = 0
        for s in range(n_trials):
            n_acc, out = fn(jnp.uint32(s))
            counts[int(np.asarray(out)[0])] += 1
            accepts += int(n_acc)
        emp = counts / n_trials
        # ~3 sigma of a binomial at n=1500 is ~0.04; the bias we are
        # guarding against (naive accept-only-on-match) is >> 0.1
        np.testing.assert_allclose(emp, target, atol=0.05)
        # acceptance tracks p(draft)
        assert abs(accepts / n_trials - target[draft_tok]) < 0.05

    def test_greedy_rows_ignore_randomness(self):
        from ray_tpu.models.sampling import speculative_verify

        logits = jnp.asarray(np.random.RandomState(1).randn(3, 10), jnp.float32)
        gr = np.argmax(np.asarray(logits), -1)
        for seed in (0, 1, 2):
            n, out = speculative_verify(
                logits, jnp.asarray(gr[:2], jnp.int32), jnp.uint32(seed),
                jnp.int32(0), temperature=0.0,
            )
            assert int(n) == 2 and list(np.asarray(out)) == list(gr)

    def test_engine_sampled_spec_reproduces_per_seed(self, tiny_params, spec_engine):
        """temperature > 0 through the spec engine: same seed reproduces
        (even though leftover backoff state shifts the window boundaries
        between the two runs — sample-then-match keys each output index
        independently of window alignment), different seed diverges, and
        the whole stream equals the NON-speculative sampled path."""
        eng = spec_engine
        p = _prompt(8)
        sp = dict(max_tokens=12, temperature=1.5)
        a = eng.generate(p, SamplingParams(seed=1, **sp))
        b = eng.generate(p, SamplingParams(seed=1, **sp))
        c = eng.generate(p, SamplingParams(seed=2, **sp))
        assert a == b, "same seed must reproduce"
        assert a != c, "different seeds should diverge at temperature 1.5"
        assert all(0 <= t < TINY.vocab_size for t in a)
        plain = _engine(tiny_params)  # spec_k=0: ordinary decode
        assert a == plain.generate(p, SamplingParams(seed=1, **sp)), (
            "sampled speculative decode must be token-identical to the "
            "non-speculative sampled path"
        )


# ---------------------------------------------------------------------------
# serve autoscaler: deployment-exported signals drive scaling
# ---------------------------------------------------------------------------


class TestAutoscalerSignals:
    def test_replica_exports_autoscaling_metrics(self):
        from ray_tpu.serve._private.replica import Replica

        class Exporting:
            def __call__(self):
                return "ok"

            def autoscaling_metrics(self):
                return {"queue_depth": 7, "kv_utilization": 0.5}

        r = Replica("r#1", Exporting, (), {})
        m = r.get_metrics()
        assert m["autoscaling_metrics"] == {"queue_depth": 7, "kv_utilization": 0.5}

        class Plain:
            def __call__(self):
                return "ok"

        assert "autoscaling_metrics" not in Replica("r#2", Plain, (), {}).get_metrics()

    def test_desired_replicas_counts_queue_depth(self):
        from ray_tpu.serve._private.common import AutoscalingConfig
        from ray_tpu.serve._private.controller import desired_replicas

        cfg = AutoscalingConfig(min_replicas=1, max_replicas=8,
                                target_ongoing_requests=2)
        # ongoing alone: 2 requests -> 1 replica
        base = [{"num_ongoing_requests": 2}]
        assert desired_replicas(cfg, base, current=1) == 1
        # same ongoing count, deep engine queue -> queued requests are load
        queued = [{
            "num_ongoing_requests": 2,
            "autoscaling_metrics": {"queue_depth": 6, "kv_utilization": 0.2},
        }]
        assert desired_replicas(cfg, queued, current=1) == 4
        # bounded by max_replicas
        flood = [{
            "num_ongoing_requests": 2,
            "autoscaling_metrics": {"queue_depth": 100},
        }]
        assert desired_replicas(cfg, flood, current=1) == 8

    def test_desired_replicas_kv_pressure_scales_up(self):
        from ray_tpu.serve._private.common import AutoscalingConfig
        from ray_tpu.serve._private.controller import desired_replicas

        cfg = AutoscalingConfig(min_replicas=1, max_replicas=4,
                                target_ongoing_requests=4,
                                kv_utilization_threshold=0.9)
        # calm request counts but a KV-saturated engine: scale up anyway
        hot = [{
            "num_ongoing_requests": 1,
            "autoscaling_metrics": {"queue_depth": 0, "kv_utilization": 0.95},
        }]
        assert desired_replicas(cfg, hot, current=2) == 3
        cool = [{
            "num_ongoing_requests": 1,
            "autoscaling_metrics": {"queue_depth": 0, "kv_utilization": 0.5},
        }]
        assert desired_replicas(cfg, cool, current=2) == 1

    def test_llm_deployment_signals_reach_the_decision(self, tiny_params):
        """End-to-end minus actors: an LLMDeployment replica's exported
        metrics, fed through the controller's pure decision function."""
        from ray_tpu.serve._private.common import AutoscalingConfig
        from ray_tpu.serve._private.controller import desired_replicas
        from ray_tpu.serve._private.replica import Replica
        from ray_tpu.serve.llm import LLMDeployment

        r = Replica(
            "llm#1",
            LLMDeployment,
            (),
            dict(
                model="gptj", model_cfg=TINY,
                engine_config=EngineConfig(
                    max_slots=1, num_blocks=16, block_size=4,
                    max_blocks_per_seq=8, prefill_chunk=8,
                ),
                warmup=False,
            ),
        )
        # no loop thread is draining the engine: submitted requests pile
        # up as queue depth behind the single slot
        for _ in range(5):
            r._callable._engine.submit([1, 2, 3], SamplingParams(max_tokens=4))
        m = r.get_metrics()
        am = m["autoscaling_metrics"]
        assert am["queue_depth"] >= 4
        cfg = AutoscalingConfig(min_replicas=1, max_replicas=4,
                                target_ongoing_requests=2)
        assert desired_replicas(cfg, [m], current=1) > 1
        r._callable._stop.set()
