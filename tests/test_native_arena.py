"""Native shared-memory arena (ray_tpu/_native/arena.cc) — the plasma
equivalent (reference: src/ray/object_manager/plasma/store.h:55, eviction
pinning in eviction_policy.cc).

Unit-tests the allocator directly (alloc/free/coalesce, pin/zombie
protocol) and the store integration (arena-placed objects round-tripping
through put/get, refcount-driven frees returning bytes to the arena).
"""

import os

import numpy as np
import pytest

from ray_tpu import _native

pytestmark = pytest.mark.skipif(
    _native.load() is None, reason="native arena unavailable (no g++?)"
)


@pytest.fixture
def arena():
    a = _native.Arena.create(f"/rta-test-{os.getpid()}-{os.urandom(4).hex()}", 1 << 22)
    assert a is not None
    yield a
    a.unlink()


class TestAllocator:
    def test_alloc_write_read(self, arena):
        off, gen = arena.alloc(1000)
        arena.view(off, 1000)[:] = b"a" * 1000
        assert bytes(arena.view(off, 4)) == b"aaaa"
        assert arena.free(off, gen) == 0
        assert arena.used == 0

    def test_cross_handle_visibility(self, arena):
        off, gen = arena.alloc(64)
        arena.view(off, 4)[:] = b"xyzw"
        other = _native.Arena.attach(arena.name)
        assert bytes(other.view(off, 4)) == b"xyzw"

    def test_full_arena_returns_none(self, arena):
        assert arena.alloc(arena.capacity * 2) is None
        r = arena.alloc(arena.capacity - 64)  # exactly fills (64B block header)
        assert r is not None
        assert arena.alloc(64) is None
        assert arena.free(*r) == 0

    def test_coalescing(self, arena):
        # fill with thirds, free all, then the whole space is one block again
        a = arena.alloc(1 << 20)
        b = arena.alloc(1 << 20)
        c = arena.alloc(1 << 20)
        for r in (b, a, c):  # free middle first: exercises both-side merges
            assert arena.free(*r) == 0
        assert arena.used == 0
        big = arena.alloc(arena.capacity - 64)
        assert big is not None
        arena.free(*big)

    def test_churn_no_leak(self, arena):
        import random

        rng = random.Random(7)
        live = []
        for _ in range(500):
            if live and rng.random() < 0.5:
                off, gen = live.pop(rng.randrange(len(live)))
                assert arena.free(off, gen) == 0
            else:
                r = arena.alloc(rng.randrange(100, 60_000))
                if r is None:
                    off, gen = live.pop(0)
                    assert arena.free(off, gen) == 0
                else:
                    live.append(r)
        for off, gen in live:
            assert arena.free(off, gen) == 0
        assert arena.used == 0 and arena.n_objects == 0

    def test_stale_generation_refused(self, arena):
        off, gen = arena.alloc(128)
        assert arena.free(off, gen) == 0
        off2, gen2 = arena.alloc(128)  # reuses the same block
        assert off2 == off and gen2 != gen
        assert not arena.pin(off, gen)  # old identity is dead
        assert arena.free(off, gen) == -1
        assert arena.free(off2, gen2) == 0

    def test_free_defers_until_unpin(self, arena):
        off, gen = arena.alloc(256)
        assert arena.pin(off, gen)
        assert arena.free(off, gen) == 1  # deferred: reader holds a pin
        assert not arena.pin(off, gen)  # zombied: no new pins
        used_before = arena.used
        arena.unpin(off)  # last unpin completes the free
        assert arena.used < used_before
        assert arena.n_objects == 0


@pytest.fixture
def small_arena_cluster():
    """Cluster whose arena is tiny (1 MiB) so exhaustion paths trigger."""
    import ray_tpu
    from ray_tpu._private.config import GLOBAL_CONFIG

    old = GLOBAL_CONFIG.object_store_arena_bytes
    ray_tpu.init(num_cpus=2, _system_config={"object_store_arena_bytes": 1 << 20})
    yield
    ray_tpu.shutdown()
    GLOBAL_CONFIG.object_store_arena_bytes = old


class TestStoreIntegration:
    def test_arena_objects_roundtrip(self, ray_start_regular):
        import ray_tpu
        from ray_tpu._private import shm_store

        assert shm_store._write_arena_name, "head should have created an arena"

        @ray_tpu.remote
        def make(n):
            return np.arange(n, dtype=np.int64)

        # >100KiB direct-call limit, <=256KiB arena cap -> arena placement
        n = 20_000
        ref = make.remote(n)
        v = ray_tpu.get(ref)
        assert v[-1] == n - 1
        arena = shm_store.attach_arena(shm_store._write_arena_name)
        assert arena.n_objects >= 1

        # freeing the ref returns the bytes to the allocator
        del ref, v
        import gc

        gc.collect()
        import time

        for _ in range(50):
            if arena.n_objects == 0:
                break
            time.sleep(0.1)
        assert arena.n_objects == 0

    def test_large_objects_use_dedicated_segments(self, ray_start_regular):
        """Objects above arena_max_object_bytes (64 MB — large objects
        recycle warmed arena pages for write throughput, see config.py) get
        a dedicated POSIX segment."""
        import ray_tpu
        from ray_tpu._private import shm_store
        from ray_tpu._private.config import GLOBAL_CONFIG

        arena = shm_store.attach_arena(shm_store._write_arena_name)
        n = GLOBAL_CONFIG.arena_max_object_bytes // 8 + 1_000_000
        before = arena.n_objects
        ref = ray_tpu.put(np.zeros(n))  # just over the arena object cap
        assert ray_tpu.get(ref).shape == (n,)
        assert arena.n_objects == before  # did not land in the arena

    def test_medium_objects_recycle_arena_pages(self, ray_start_regular):
        """A 10 MB object lands in the arena (zero-copy pinned reads; write
        path recycles faulted pages instead of paying per-put page faults)."""
        import ray_tpu
        from ray_tpu._private import shm_store

        arena = shm_store.attach_arena(shm_store._write_arena_name)
        before = arena.n_objects
        src = np.arange(1_250_000, dtype=np.float64)  # 10 MB
        ref = ray_tpu.put(src)
        assert arena.n_objects == before + 1
        out = ray_tpu.get(ref)
        assert (out[::100_000] == src[::100_000]).all()
        # zero-copy: the value's buffer lives in the shared mapping, and the
        # block stays pinned (a free would defer) while the view is alive
        del out
        del ref  # free the object; block returns to the allocator
        import gc

        gc.collect()
        deadline = 50
        while arena.n_objects != before and deadline:
            import time

            time.sleep(0.1)
            deadline -= 1
        assert arena.n_objects == before

    def test_arena_exhaustion_falls_back(self, small_arena_cluster):
        """When the arena fills, writes degrade to dedicated segments."""
        import ray_tpu
        from ray_tpu._private import shm_store

        refs = [ray_tpu.put(np.zeros(25_000)) for _ in range(40)]  # 200KB each
        vals = ray_tpu.get(refs)
        assert all(v.shape == (25_000,) for v in vals)
        arena = shm_store.attach_arena(shm_store._write_arena_name)
        # 40 x 200KB = 8MB >> 1MiB arena -> most fell back to segments
        assert arena.used <= arena.capacity
