#!/usr/bin/env bash
# Local pre-commit gate: what CI runs, runnable in one command.
#   tools/check.sh          # lint + import check + tier-1 tests
#   tools/check.sh --fast   # lint + import check only (seconds)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== raylint =="
python -m ray_tpu.lint ray_tpu/

echo "== import cycles / py_compile =="
python -m ray_tpu.lint ray_tpu/ --check-imports

if [[ "${1:-}" != "--fast" ]]; then
    echo "== tier-1 tests =="
    JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider
fi

echo "OK"
