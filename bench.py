"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric: single-chip GPT training throughput (tokens/sec) on the
flagship decoder-only model, bf16 compute.

``vs_baseline`` normalizes across hardware and model size via MFU (model
FLOPs utilization, train FLOPs ≈ 6·N·tokens): the reference's headline
training number is the GPT-J-6B DeepSpeed ZeRO-3 fine-tune at 4.565
samples/s × 512 tokens on 16× T4 (`release/air_examples/
gptj_deepspeed_finetuning/gptj_deepspeed_fine_tuning.ipynb`, BASELINE.md) →
146 tokens/s/GPU → 6·6.05e9·146 / 65e12 (T4 fp16 peak) ≈ 8.15% MFU.
``vs_baseline`` = our MFU / 0.0815, so >1.0 means better hardware
utilization than the reference's own headline run.
"""

from __future__ import annotations

import json
import time

REF_MFU = 0.0815  # reference GPT-J-6B fine-tune (see module docstring)

PEAK_FLOPS = {
    # per-chip dense bf16 peak
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "TPU v2": 46e12,
    "TPU v6 lite": 918e12,   # v6e
    "TPU v6e": 918e12,
    "TPU v7": 4614e12,       # ironwood
    "cpu": 1e12,             # nominal, for smoke runs without a TPU
}
_MAX_TPU_PEAK = max(v for k, v in PEAK_FLOPS.items() if k != "cpu")


def _peak_for(device) -> tuple[float, bool]:
    """(peak_flops, assumed). Unknown TPU kinds assume the highest known peak
    so MFU/vs_baseline are understated, never inflated."""
    kind = str(getattr(device, "device_kind", "cpu")).lower()
    for name, peak in PEAK_FLOPS.items():
        if name.lower() in kind:
            return peak, False
    if "tpu" in kind:
        return _MAX_TPU_PEAK, True
    return PEAK_FLOPS["cpu"], True


def main():
    # core microbench first: it is CPU-only and must not run while this
    # process holds the single-tenant TPU tunnel (import jax acquires it)
    core = _core_microbench()
    fit = _gptj_fit_proof()

    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.gpt import GPTConfig, gpt_init, gpt_loss
    from ray_tpu.parallel.mesh import MeshConfig, make_mesh
    from ray_tpu.parallel.train_step import build_train_step

    dev = jax.devices()[0]
    on_tpu = "tpu" in str(getattr(dev, "platform", "")).lower()
    if on_tpu:
        # 406M-param GPT, bf16, Pallas flash attention (1024x1024 blocks),
        # fused blockwise cross-entropy with LANE-ALIGNED chunks (vocab
        # 50304 -> 3 chunks of 16768; the old power-of-two auto-pick's
        # 1572-wide chunks padded on the MXU, ~1% whole-step cost), remat
        # policy "attn" (keeps only flash out+lse; at batch 24 the extra
        # HBM of "big" loses to the larger batch). Round-4 sweep on v5e,
        # honest host-transfer barrier, median-of-3: batch 24 attn 0.423 >
        # 24 big 0.418 > 16 big 0.412 (round-3 config) > 24 dots 0.39;
        # bwd blocks 512/256, scan unroll 2/4, XLA attention, bf16 adam
        # moments, batches 28/32, and no-remat (OOM <= batch 8) all lose.
        cfg = GPTConfig(
            vocab_size=50_304, seq_len=1024, d_model=1024, n_layers=24, n_heads=16,
            remat_policy="attn",
        )
        batch = 24
        steps = 8
    else:  # smoke config for CPU-only environments
        cfg = GPTConfig(vocab_size=1024, seq_len=128, d_model=128, n_layers=2, n_heads=4)
        batch = 4
        steps = 2

    mesh = make_mesh(MeshConfig(dp=1, fsdp=1, tp=1, sp=1), devices=[dev])

    def loss_fn(params, tokens):
        return gpt_loss(cfg, params, tokens, mesh)

    init_fn, step_fn = build_train_step(loss_fn, optax.adamw(1e-4), mesh)
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))
    state = init_fn(params)
    del params

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, cfg.seq_len + 1), 0, cfg.vocab_size, jnp.int32
    )
    tokens = jax.device_put(tokens, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()))

    def barrier(state, loss):
        """Host transfers are the only reliable completion barrier through
        the remote-execution tunnel (block_until_ready can return before
        the work drains). Pull one UPDATED param element, not just the
        loss — the loss is computed before the optimizer writes, so a
        loss-only barrier would exclude the final update's tail."""
        float(loss)
        leaf = jax.tree_util.tree_leaves(state)[0]
        float(jnp.ravel(leaf)[0])

    # warmup / compile
    state, loss = step_fn(state, tokens)
    barrier(state, loss)

    # The tunnel's throughput fluctuates run to run; take the MEDIAN of
    # three windows — robust to one bad window without switching the
    # metric to best-case (the reference baseline is a sustained average).
    dts = []
    for _ in range(3 if on_tpu else 1):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = step_fn(state, tokens)
        barrier(state, loss)
        dts.append(time.perf_counter() - t0)
    dt = sorted(dts)[len(dts) // 2]

    tok_per_step = batch * cfg.seq_len
    tok_per_sec = steps * tok_per_step / dt
    peak, peak_assumed = _peak_for(dev)
    mfu = 6.0 * n_params * tok_per_sec / peak

    detail = {
        "model_params": n_params,
        "mfu": round(mfu, 4),
        "device": str(getattr(dev, "device_kind", dev)),
        "peak_flops_assumed": peak_assumed,
        "loss": float(loss),
    }
    detail["core"] = core
    if fit:
        detail["gptj_6b_compiles"] = bool(fit.get("compiles"))
        detail["gptj_6b_fit"] = fit
    print(
        json.dumps(
            {
                "metric": "gpt_train_tokens_per_sec_per_chip",
                "value": round(tok_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(mfu / REF_MFU, 3),
                "detail": detail,
            }
        )
    )


def _core_microbench() -> dict:
    """Runtime-core throughput next to the training metric (VERDICT asked
    for the reference's ray_perf metric names in BENCH reporting). Runs in
    a subprocess so a runtime-side failure can never cost the headline
    number; returns {} on any problem."""
    import os
    import subprocess
    import sys

    try:
        env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_core.py")],
            capture_output=True,
            text=True,
            timeout=600,
            env=env,
        )
        for line in reversed(out.stdout.splitlines()):
            if line.startswith("{"):
                rec = json.loads(line)
                if rec.get("metric") == "core_microbench":
                    detail = rec.get("detail", {})
                    if rec.get("env"):
                        # Contention context (cpu count, loadavg, spin
                        # canary) so cross-round comparisons of the core
                        # numbers are interpretable (VERDICT r4 #1a).
                        detail["_env"] = rec["env"]
                    return detail
        print(
            f"[bench] core microbench produced no metrics (rc={out.returncode}): "
            f"{out.stderr[-500:]}",
            file=sys.stderr,
        )
        return {}
    except Exception as e:
        print(f"[bench] core microbench failed: {e!r}", file=sys.stderr)
        return {}


def _gptj_fit_proof() -> dict:
    """GPT-J-6B fsdp-8 AOT fit proof on a virtual CPU mesh (subprocess: it
    must not inherit this process's TPU backend, and a failure must not
    cost the headline number). See ray_tpu/parallel/fit_proof.py."""
    import os
    import subprocess
    import sys

    try:
        env = dict(
            os.environ,
            PALLAS_AXON_POOL_IPS="",
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8"
            ).strip(),
        )
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.parallel.fit_proof"],
            capture_output=True,
            text=True,
            timeout=900,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in reversed(out.stdout.splitlines()):
            if line == "{" or line.startswith('{"'):
                return json.loads(line)
        print(
            f"[bench] gptj fit proof produced no report (rc={out.returncode}): "
            f"{out.stderr[-500:]}",
            file=sys.stderr,
        )
        return {}
    except Exception as e:
        print(f"[bench] gptj fit proof failed: {e!r}", file=sys.stderr)
        return {}


if __name__ == "__main__":
    main()
