"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric: single-chip GPT training throughput (tokens/sec) on the
flagship decoder-only model, bf16 compute.

Robustness (VERDICT r5): every section runs ISOLATED behind ``_section``
— a retry-once-on-transient-failure wrapper (the remote-compile tunnel
drops connections; one flaky compile used to zero a whole round's
numbers) that records per-section status, prints a per-section JSON line
the moment the section finishes (so a later crash can't erase earlier
results), and ALWAYS lets the final record go out with whatever sections
succeeded — a failed headline reports value 0 with its error attached
instead of printing nothing.

``vs_baseline`` normalizes across hardware and model size via MFU (model
FLOPs utilization, train FLOPs ≈ 6·N·tokens): the reference's headline
training number is the GPT-J-6B DeepSpeed ZeRO-3 fine-tune at 4.565
samples/s × 512 tokens on 16× T4 (`release/air_examples/
gptj_deepspeed_finetuning/gptj_deepspeed_fine_tuning.ipynb`, BASELINE.md) →
146 tokens/s/GPU → 6·6.05e9·146 / 65e12 (T4 fp16 peak) ≈ 8.15% MFU.
``vs_baseline`` = our MFU / 0.0815, so >1.0 means better hardware
utilization than the reference's own headline run.
"""

from __future__ import annotations

import json
import time

REF_MFU = 0.0815  # reference GPT-J-6B fine-tune (see module docstring)

PEAK_FLOPS = {
    # per-chip dense bf16 peak
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "TPU v2": 46e12,
    "TPU v6 lite": 918e12,   # v6e
    "TPU v6e": 918e12,
    "TPU v7": 4614e12,       # ironwood
    "cpu": 1e12,             # nominal, for smoke runs without a TPU
}
_MAX_TPU_PEAK = max(v for k, v in PEAK_FLOPS.items() if k != "cpu")


def _peak_for(device) -> tuple[float, bool]:
    """(peak_flops, assumed). Unknown TPU kinds assume the highest known peak
    so MFU/vs_baseline are understated, never inflated."""
    kind = str(getattr(device, "device_kind", "cpu")).lower()
    for name, peak in PEAK_FLOPS.items():
        if name.lower() in kind:
            return peak, False
    if "tpu" in kind:
        return _MAX_TPU_PEAK, True
    return PEAK_FLOPS["cpu"], True


def _section(sections: dict, name: str, fn):
    """Run one bench section isolated: retry ONCE on failure (the
    remote-compile tunnel drops connections transiently), record status,
    and emit the section's own JSON line immediately so a later crash
    cannot erase it.  Returns the section result, or None when both
    attempts failed (subprocess-wrapped sections signal failure by
    returning an empty dict)."""
    import sys

    rec: dict = {"section": name, "ok": False, "attempts": 0}
    result = None
    for attempt in (1, 2):
        rec["attempts"] = attempt
        try:
            result = fn()
            if result:
                rec["ok"] = True
                rec.pop("error", None)  # attempt 1's transient failure
                break
            rec["error"] = "empty result"
        except Exception as e:  # noqa: BLE001 — isolation is the point
            rec["error"] = f"{type(e).__name__}: {e}"
            result = None
        if attempt == 1:
            print(
                f"[bench] section {name} failed ({rec.get('error')}); "
                "retrying once",
                file=sys.stderr,
            )
    sections[name] = rec
    print(json.dumps(rec), flush=True)
    return result


def main():
    sections: dict = {}
    core = {}
    llm = {}
    phases_ab = {}
    prefix = {}
    fit = {}
    train = {}
    silicon = {}
    try:
        # core microbench first: it is CPU-only and must not run while this
        # process holds the single-tenant TPU tunnel (import jax acquires it)
        core = _section(sections, "core_microbench", _core_microbench) or {}
        core_obs = _section(sections, "core_obs_ab", _core_obs_ab) or {}
        llm = _section(sections, "llm_serving", _llm_serving_bench) or {}
        phases_ab = _section(sections, "llm_phases_ab", _llm_phases_ab) or {}
        prefix = _section(sections, "llm_prefix", _llm_prefix_bench) or {}
        fit = _section(sections, "gptj_fit_proof", _gptj_fit_proof) or {}
        train = _section(sections, "train_headline", _train_headline) or {}

        if train.get("on_tpu"):
            # _train_headline's state is freed with its frame — the 6B
            # forward gets the HBM back before this section allocates
            silicon = _section(sections, "gptj_6b_silicon", _gptj_6b_silicon) or {}
    finally:
        # the headline ALWAYS prints — even if a section escapes _section's
        # isolation with a BaseException (the BENCH_r05 failure mode: one
        # remote_compile infra flake, rc=1, and the whole round's
        # trajectory was lost). Whatever sections completed go out.
        detail = dict(train.get("detail", {}))
        detail["core"] = core
        if core_obs:
            # recorder+series ON vs OFF on the task/object hot path — the
            # attribution probe for the r04 core-plane collapse (ROADMAP)
            detail["core_obs_ab"] = core_obs
        if llm:
            # continuous-batching serving engine vs sequential static-batch
            # decode under staggered arrivals + speculative-decode
            # comparison (ray_tpu/llm/bench.py)
            detail["llm_serving"] = llm
        if phases_ab:
            # per-request phase-ledger stamping ON vs OFF on the engine hot
            # loops — the attribution plane's overhead acquittal (≤5%)
            detail["llm_phases_ab"] = phases_ab
        if prefix:
            # cross-request prefix cache on the shared-system-prompt
            # workload: prefill-tokens-computed + warm TTFT, on vs off
            detail["llm_prefix"] = prefix
        if fit:
            detail["gptj_6b_compiles"] = bool(fit.get("compiles"))
            detail["gptj_6b_fit"] = fit
        if train.get("on_tpu"):
            detail.update(silicon)
        detail["sections"] = sections
        print(
            json.dumps(
                {
                    "metric": "gpt_train_tokens_per_sec_per_chip",
                    "value": train.get("value", 0.0),
                    "unit": "tokens/s",
                    "vs_baseline": train.get("vs_baseline", 0.0),
                    "detail": detail,
                }
            )
        )


def _train_headline() -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.gpt import GPTConfig, gpt_init, gpt_loss
    from ray_tpu.parallel.mesh import MeshConfig, make_mesh
    from ray_tpu.parallel.train_step import build_train_step

    dev = jax.devices()[0]
    on_tpu = "tpu" in str(getattr(dev, "platform", "")).lower()
    if on_tpu:
        # 406M-param GPT, bf16, Pallas flash attention (1024x1024 blocks),
        # fused cross-entropy with ONE full-width pass (ce_chunks=1: at
        # this shape the (tokens,vocab) fp32 transient fits and beats the
        # lane-aligned 3-chunk streaming by ~1 MFU point), remat policy
        # "attn" (keeps only flash out+lse), batch 26. Round-5 sweep on
        # v5e (honest host-transfer barrier, best-of-2 triage windows,
        # winners confirmed median-of-3): 26/attn/ce1 0.431-0.435 >
        # 24/attn/ce1 0.423-0.426 > 24/attn/ce3 0.410-0.414 (round-4
        # config) > 27 or 28/ce1, big@16-20/ce1, attn_qkv (new policy —
        # saving qkv LOSES, extra HBM reads beat the matmul saved),
        # CE_SAVE_LOGITS (no win: XLA overlaps the recompute), fwd flash
        # blocks 512, bwd 512, scan unroll 2, 6-step fused lax.scan loop
        # (same as per-step dispatch: the tunnel pipeline isn't the gap).
        cfg = GPTConfig(
            vocab_size=50_304, seq_len=1024, d_model=1024, n_layers=24, n_heads=16,
            remat_policy="attn", ce_chunks=1,
        )
        batch = 26
        steps = 8
    else:  # smoke config for CPU-only environments
        cfg = GPTConfig(vocab_size=1024, seq_len=128, d_model=128, n_layers=2, n_heads=4)
        batch = 4
        steps = 2

    tpu_canary = None
    if on_tpu:
        # Tunnel-health canary (the TPU analog of bench_core's spin canary):
        # a fixed 8192^2 bf16 matmul chain measured before the training
        # loop. The tunnel is shared/remote and its throughput can collapse
        # ~20x under relay contention (observed live: an otherwise-identical
        # bench run recorded MFU 0.018 vs 0.433 minutes apart) — without
        # this number a reader cannot tell that apart from a regression.
        x = jnp.ones((8192, 8192), jnp.bfloat16)
        mm = jax.jit(lambda a: a @ a)
        r = mm(x)
        float(jnp.ravel(r)[0])
        t0 = time.perf_counter()
        for _ in range(10):
            r = mm(r)
        float(jnp.ravel(r)[0])
        tpu_canary = round(10 * 2 * 8192**3 / (time.perf_counter() - t0) / 1e12, 1)
        del x, r

    mesh = make_mesh(MeshConfig(dp=1, fsdp=1, tp=1, sp=1), devices=[dev])

    def loss_fn(params, tokens):
        return gpt_loss(cfg, params, tokens, mesh)

    init_fn, step_fn = build_train_step(loss_fn, optax.adamw(1e-4), mesh)
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))
    state = init_fn(params)
    del params

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, cfg.seq_len + 1), 0, cfg.vocab_size, jnp.int32
    )
    tokens = jax.device_put(tokens, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()))

    def barrier(state, loss):
        """Host transfers are the only reliable completion barrier through
        the remote-execution tunnel (block_until_ready can return before
        the work drains). Pull one UPDATED param element, not just the
        loss — the loss is computed before the optimizer writes, so a
        loss-only barrier would exclude the final update's tail."""
        float(loss)
        leaf = jax.tree_util.tree_leaves(state)[0]
        float(jnp.ravel(leaf)[0])

    # warmup / compile
    state, loss = step_fn(state, tokens)
    barrier(state, loss)

    # The tunnel's throughput fluctuates run to run; take the MEDIAN of
    # three windows — robust to one bad window without switching the
    # metric to best-case (the reference baseline is a sustained average).
    dts = []
    for _ in range(3 if on_tpu else 1):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = step_fn(state, tokens)
        barrier(state, loss)
        dts.append(time.perf_counter() - t0)
    dt = sorted(dts)[len(dts) // 2]

    tok_per_step = batch * cfg.seq_len
    tok_per_sec = steps * tok_per_step / dt
    peak, peak_assumed = _peak_for(dev)
    mfu = 6.0 * n_params * tok_per_sec / peak

    detail = {
        "model_params": n_params,
        "mfu": round(mfu, 4),
        "device": str(getattr(dev, "device_kind", dev)),
        "peak_flops_assumed": peak_assumed,
        "loss": float(loss),
    }
    if tpu_canary is not None:
        # healthy v5e measures ~100 TFLOPs here; a collapsed tunnel shows
        # single digits — read mfu in that light
        detail["tpu_canary_matmul_tflops"] = tpu_canary
    return {
        "value": round(tok_per_sec, 1),
        "vs_baseline": round(mfu / REF_MFU, 3),
        "detail": detail,
        "on_tpu": on_tpu,
    }


def _run_bench_core(metric: str, extra_args=(), env_overrides=None, timeout=600) -> dict:
    """Run ``bench_core.py`` in a CPU-only subprocess (it must never touch
    the single-tenant TPU tunnel) and return the JSON record whose
    ``metric`` matches — one scaffold for every core section, so the
    emission protocol / env guards / diagnostics stay in one place.
    Returns {} on any problem (a runtime-side failure never costs the
    headline number)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    env.update(env_overrides or {})
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "bench_core.py"
            ),
            *extra_args,
        ],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
    )
    for line in reversed(out.stdout.splitlines()):
        if line.startswith("{"):
            rec = json.loads(line)
            if rec.get("metric") == metric:
                return rec
    print(
        f"[bench] bench_core {metric} produced no record (rc={out.returncode}): "
        f"{out.stderr[-500:]}",
        file=sys.stderr,
    )
    return {}


def _core_microbench() -> dict:
    """Runtime-core throughput next to the training metric (VERDICT asked
    for the reference's ray_perf metric names in BENCH reporting)."""
    import sys

    try:
        rec = _run_bench_core("core_microbench")
        detail = rec.get("detail", {})
        if rec.get("env"):
            # Contention context (cpu count, loadavg, spin canary) so
            # cross-round comparisons of the core numbers are
            # interpretable (VERDICT r4 #1a).
            detail["_env"] = rec["env"]
        return detail
    except Exception as e:
        print(f"[bench] core microbench failed: {e!r}", file=sys.stderr)
        return {}


def _core_obs_ab() -> dict:
    """Observability-overhead A/B on the core task/object hot path
    (ROADMAP "core-plane throughput regression"): run
    ``bench_core.py --obs-ab`` twice in subprocesses — flight recorder +
    metric time-series ON, then OFF (both knobs are import-time, so a
    fresh process per arm is the only honest A/B) — and report both
    numbers plus the ON/OFF ratio per microbench.  A ratio well below
    1.0 says the recorder/series machinery owns that share of the r04
    collapse; a ratio ≈ 1.0 acquits it.  CPU-only subprocesses for the
    same tunnel-safety reason as the core microbench."""
    import sys

    def one_arm(obs_on: bool) -> dict:
        flag = "1" if obs_on else "0"
        rec = _run_bench_core(
            "core_obs_ab", extra_args=("--obs-ab",),
            env_overrides={"RAY_TPU_EVENTS": flag,
                           "RAY_TPU_METRICS_SERIES": flag},
            timeout=300,
        )
        return rec.get("detail", {})

    try:
        on = one_arm(True)
        off = one_arm(False)
        if not on or not off:
            return {}
        ratios = {
            k: round(on[k] / off[k], 4)
            for k in on
            if k in off and off[k] > 0
        }
        return {"obs_on": on, "obs_off": off, "on_over_off_ratio": ratios}
    except Exception as e:
        print(f"[bench] core obs A/B failed: {e!r}", file=sys.stderr)
        return {}


def _llm_serving_bench() -> dict:
    """Continuous-batching vs static-batch decode throughput under
    staggered arrivals, plus the speculative-decode comparison
    (``python -m ray_tpu.llm.bench`` prints one record per benchmark).
    CPU-only subprocess for the same reason as the core microbench: it
    must not touch the TPU tunnel, and a failure costs only this field."""
    import os
    import subprocess
    import sys

    try:
        env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
        out = subprocess.run(
            # just the serving benches — the prefix workload has its own
            # section (_llm_prefix_bench) and must not run twice
            [sys.executable, "-m", "ray_tpu.llm.bench", "--only", "serving"],
            capture_output=True,
            text=True,
            timeout=600,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        result: dict = {}
        for line in out.stdout.splitlines():
            if not line.startswith("{"):
                continue
            rec = json.loads(line)
            if rec.get("metric") == "llm_continuous_batching_tokens_per_sec":
                result.update(
                    {
                        "continuous_tokens_per_sec": rec["value"],
                        "speedup_vs_static": rec["vs_baseline"],
                        **rec.get("detail", {}),
                    }
                )
            elif rec.get("metric") == "llm_speculative_decode_speedup":
                result["speculative"] = {
                    "spec_tokens_per_sec": rec["value"],
                    "speedup_vs_nonspec": rec["vs_baseline"],
                    **rec.get("detail", {}),
                }
        if result:
            return result
        print(
            f"[bench] llm serving bench produced no metrics (rc={out.returncode}): "
            f"{out.stderr[-500:]}",
            file=sys.stderr,
        )
        return {}
    except Exception as e:
        print(f"[bench] llm serving bench failed: {e!r}", file=sys.stderr)
        return {}


def _llm_phases_ab() -> dict:
    """Phase-ledger stamping ON vs OFF on the continuous-batching engine
    (``python -m ray_tpu.llm.bench --only continuous``), same honest-A/B
    shape as ``_core_obs_ab``: ``RAY_TPU_PHASES`` is import-time, so each
    arm is a fresh CPU-only subprocess.  The per-request ledger rides the
    engine's admission/prefill/decode hot loops — a ratio ≈ 1.0 says the
    stamping (a list add + two float ops per transition, zero locks)
    stays within noise; the acceptance bar is OFF/ON ≤ 1.05."""
    import os
    import subprocess
    import sys

    def one_arm(phases_on: bool) -> float:
        env = dict(
            os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
            RAY_TPU_PHASES="1" if phases_on else "0",
        )
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.llm.bench", "--only", "continuous"],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in out.stdout.splitlines():
            if line.startswith("{"):
                rec = json.loads(line)
                if rec.get("metric") == "llm_continuous_batching_tokens_per_sec":
                    return float(rec["value"])
        raise RuntimeError(
            f"no continuous record (rc={out.returncode}): {out.stderr[-300:]}"
        )

    try:
        on = one_arm(True)
        off = one_arm(False)
        return {
            "phases_on_tokens_per_sec": on,
            "phases_off_tokens_per_sec": off,
            "on_over_off_ratio": round(on / off, 4) if off else None,
        }
    except Exception as e:
        print(f"[bench] llm phases A/B failed: {e!r}", file=sys.stderr)
        return {}


def _llm_prefix_bench() -> dict:
    """Cross-request prefix cache on the shared-system-prompt workload
    (``python -m ray_tpu.llm.bench --only prefix``): N requests with a
    common 256-token prefix, cache on vs off — prefill tokens computed,
    warm-request TTFT, token-identity asserted in the subprocess.
    CPU-only subprocess like the other llm sections."""
    import os
    import subprocess
    import sys

    try:
        env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.llm.bench", "--only", "prefix"],
            capture_output=True,
            text=True,
            timeout=600,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in out.stdout.splitlines():
            if not line.startswith("{"):
                continue
            rec = json.loads(line)
            if rec.get("metric") == "llm_prefix_cache_warm_ttft_speedup":
                return {
                    "warm_ttft_speedup": rec["value"],
                    **rec.get("detail", {}),
                }
        print(
            f"[bench] llm prefix bench produced no metric (rc={out.returncode}): "
            f"{out.stderr[-500:]}",
            file=sys.stderr,
        )
        return {}
    except Exception as e:
        print(f"[bench] llm prefix bench failed: {e!r}", file=sys.stderr)
        return {}


def _gptj_6b_silicon() -> dict:
    """GPT-J-6B on the real chip (VERDICT r4 #4): a full bf16 forward at
    seq 2048 and a short KV-cache greedy decode, with the true GPT-J
    architecture (models/gptj.py — the HF-checkpoint-import target whose
    conversion is logit-exact, test_train_integrations.py::TestGPTJ).
    Weights are seeded-random AT THE 6B SHAPE, generated directly on
    device in bf16 (12.1 GiB — real checkpoint bytes cannot enter this
    zero-egress environment, and the arithmetic is weight-value-
    independent). Failure costs only these fields, never the headline."""
    import gc

    gc.collect()
    try:
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.gptj import (
            GPTJConfig,
            gptj_decode,
            gptj_forward,
            gptj_init,
        )

        cfg = GPTJConfig(
            vocab_size=50_432,  # HF 50400 padded to the MXU lane multiple
            remat=False,  # inference: no backward to rematerialize for
            dtype="bfloat16",
        )

        def init_bf16():
            p = gptj_init(jax.random.PRNGKey(7), cfg)
            return jax.tree.map(lambda x: x.astype(jnp.bfloat16), p)

        params = jax.jit(init_bf16)()  # generated on-device: no 24 GB host tree
        jax.block_until_ready(params)
        n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))

        fwd = jax.jit(lambda p, t: gptj_forward(cfg, p, t))
        tokens = jnp.asarray(
            jax.random.randint(jax.random.PRNGKey(8), (1, 2048), 0, 50_400),
            jnp.int32,
        )
        logits = fwd(params, tokens)
        float(jnp.ravel(logits)[0])  # compile + transfer barrier
        dts = []
        for _ in range(3):
            t0 = time.perf_counter()
            logits = fwd(params, tokens)
            float(jnp.ravel(logits)[0])
            dts.append(time.perf_counter() - t0)
        fwd_tok_s = 2048 / sorted(dts)[1]

        n_new = 16
        dec = jax.jit(lambda p, t: gptj_decode(cfg, p, t, n_new))
        prompt = tokens[:, :128]
        out = dec(params, prompt)
        int(out[0, -1])
        t0 = time.perf_counter()
        out = dec(params, prompt)
        int(out[0, -1])
        dec_tok_s = n_new / (time.perf_counter() - t0)
        return {
            "gptj_6b_params": n_params,
            "gptj_6b_forward_tokens_per_sec": round(fwd_tok_s, 1),
            "gptj_6b_decode_tokens_per_sec": round(dec_tok_s, 1),
        }
    except Exception as e:  # noqa: BLE001
        import sys

        print(f"[bench] gptj 6b silicon failed: {e!r}", file=sys.stderr)
        return {}


def _gptj_fit_proof() -> dict:
    """GPT-J-6B fsdp-8 AOT fit proof on a virtual CPU mesh (subprocess: it
    must not inherit this process's TPU backend, and a failure must not
    cost the headline number). See ray_tpu/parallel/fit_proof.py."""
    import os
    import subprocess
    import sys

    try:
        env = dict(
            os.environ,
            PALLAS_AXON_POOL_IPS="",
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8"
            ).strip(),
        )
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.parallel.fit_proof"],
            capture_output=True,
            text=True,
            timeout=900,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in reversed(out.stdout.splitlines()):
            if line == "{" or line.startswith('{"'):
                return json.loads(line)
        print(
            f"[bench] gptj fit proof produced no report (rc={out.returncode}): "
            f"{out.stderr[-500:]}",
            file=sys.stderr,
        )
        return {}
    except Exception as e:
        print(f"[bench] gptj fit proof failed: {e!r}", file=sys.stderr)
        return {}


if __name__ == "__main__":
    main()
